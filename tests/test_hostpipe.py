"""Multiprocess verify/codec pipeline (server/hostpipe.py): pool
round-trips, sticky routing, crash semantics, verify fan-out, and the
grapevine_host_* telemetry leak policy."""

import os
import signal
import time

import grpc
import pytest

from grapevine_tpu.config import GrapevineConfig
from grapevine_tpu.obs import TelemetryRegistry
from grapevine_tpu.server.client import GrapevineClient
from grapevine_tpu.server.hostpipe import (
    HostAuthError,
    HostPipeline,
    HostWorkerCrash,
)
from grapevine_tpu.server.service import GrapevineServer
from grapevine_tpu.session import get_signature_scheme, schnorrkel
from grapevine_tpu.session.chacha import ChallengeRng
from grapevine_tpu.session.channel import (
    client_finish,
    client_handshake,
    server_handshake,
)
from grapevine_tpu.wire import constants as C
from grapevine_tpu.wire.records import QueryRequest, RequestRecord

CFG = GrapevineConfig(
    bucket_cipher_rounds=0, max_messages=64, max_recipients=8,
    mailbox_cap=8, batch_size=4, stash_size=64,
)


def pl(text: bytes) -> bytes:
    return text.ljust(C.PAYLOAD_SIZE, b"\x00")


def _wait_until(pred, timeout=10.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}")


# -- pool unit tests (no engine, no gRPC) ------------------------------


@pytest.fixture(scope="module")
def pool():
    reg = TelemetryRegistry()
    p = HostPipeline(2, registry=reg)
    p.test_registry = reg
    yield p
    p.close()


def _attached_session(pool, cid=b"C" * 16, seed=b"\x07" * 32):
    """Handshake a channel pair and attach the server side to the pool;
    returns (client_channel, seed)."""
    state, msg1 = client_handshake()
    reply, server_chan = server_handshake(msg1)
    client_chan = client_finish(state, reply)
    idx, epoch = pool.attach_session(cid, server_chan, seed)
    assert idx == pool.worker_for(cid)
    assert epoch == pool.epoch_of(idx)
    return client_chan, seed


def _signed_request(challenge):
    sk, _ = schnorrkel.expand_mini_secret(b"\x01" * 32)
    pub = schnorrkel.public_key(sk)
    sig = schnorrkel.sign(
        sk, C.GRAPEVINE_CHALLENGE_SIGNING_CONTEXT, challenge
    )
    return QueryRequest(
        request_type=C.REQUEST_TYPE_CREATE,
        auth_identity=pub,
        auth_signature=sig,
        record=RequestRecord(recipient=pub, payload=pl(b"via-pool")),
    )


def test_sticky_routing_is_public_and_deterministic(pool):
    import hashlib

    for cid in (b"a" * 16, b"b" * 16, os.urandom(16)):
        want = int.from_bytes(
            hashlib.sha256(cid).digest()[:8], "big"
        ) % pool.workers
        assert pool.worker_for(cid) == want
        assert pool.worker_for(cid) == pool.worker_for(cid)


def test_open_seal_roundtrip_preserves_lockstep(pool):
    cid = b"R" * 16
    client_chan, seed = _attached_session(pool, cid=cid)
    rng = ChallengeRng(seed)  # the client's mirror of the lockstep
    for i in range(3):
        expected = rng.next_challenge()
        req = _signed_request(expected)
        ct = client_chan.encrypt(req.pack())
        got_req, got_challenge = pool.open_request(cid, ct, b"")
        assert got_challenge == expected
        assert got_req.pack() == req.pack()
        sealed = pool.seal_response(cid, b"resp-%d" % i)
        assert client_chan.decrypt(sealed) == b"resp-%d" % i


def test_injected_garbage_fails_without_desync(pool):
    """AEAD failure inside a worker must not advance cipher state or
    consume a challenge — the injection-DoS immunity of the in-process
    path (service._query) carries over to the pool."""
    cid = b"I" * 16
    client_chan, seed = _attached_session(pool, cid=cid)
    rng = ChallengeRng(seed)
    with pytest.raises(HostAuthError):
        pool.open_request(cid, b"\x13" * 128, b"")
    # the session still works and the challenge stream was not consumed
    expected = rng.next_challenge()
    req = _signed_request(expected)
    _, got = pool.open_request(cid, client_chan.encrypt(req.pack()), b"")
    assert got == expected
    sealed = pool.seal_response(cid, b"still-synced")
    assert client_chan.decrypt(sealed) == b"still-synced"


def test_unknown_channel_is_auth_error(pool):
    with pytest.raises(HostAuthError):
        pool.open_request(b"\xee" * 16, b"x" * 64, b"")


def test_verify_parallel_good_and_bad(pool):
    scheme = get_signature_scheme("schnorrkel")
    items = []
    for i in range(8):
        sk, _ = scheme.expand_mini_secret(bytes([i + 1]) * 32)
        msg = b"challenge-%d" % i
        items.append((
            scheme.public_key(sk),
            C.GRAPEVINE_CHALLENGE_SIGNING_CONTEXT,
            msg,
            scheme.sign(sk, C.GRAPEVINE_CHALLENGE_SIGNING_CONTEXT, msg),
        ))
    assert pool.verify_parallel(items) is True
    assert pool.verify_parallel([]) is True
    bad = list(items)
    bad[3] = (bad[3][0], bad[3][1], bad[3][2], b"\x00" * 64)
    assert pool.verify_parallel(bad) is False


def test_host_telemetry_families_registered(pool):
    reg = pool.test_registry
    for fam in ("grapevine_host_workers", "grapevine_host_workers_alive",
                "grapevine_host_inflight_tasks",
                "grapevine_host_tasks_total",
                "grapevine_host_worker_crash_total"):
        assert reg.get(fam) is not None, fam
    assert reg.get("grapevine_host_workers").get() == 2
    # the pool has served tasks above; phase/worker label values are the
    # declared enumerations only, and the whole registry audits clean
    assert reg.audit()["ok"]


def test_worker_label_rejects_channel_ids():
    """Teeth: a channel_id (or anything non-index) as a `worker` label
    value must raise TelemetryLeakError at registration — the declared-
    values-only policy is what keeps the worker key safe to allow."""
    from grapevine_tpu.obs.registry import TelemetryLeakError

    reg = TelemetryRegistry()
    with pytest.raises(TelemetryLeakError):
        reg.counter("bad_host_counter", "x",
                    labels={"worker": ("deadbeef" * 4,)})
    with pytest.raises(TelemetryLeakError):
        reg.counter("bad_host_counter2", "x", labels={"worker": ("w0",)})


def test_crash_fails_inflight_and_bumps_epoch():
    """Kill a worker: in-flight tasks fail with HostWorkerCrash, the
    epoch bumps (stale sessions can never resume), crash listeners get
    the index, and without restart_on_crash the pool reads degraded."""
    pool = HostPipeline(2)
    try:
        crashed = []
        pool.on_crash(crashed.append)
        cid = b"K" * 16
        _attached_session(pool, cid=cid)
        idx = pool.worker_for(cid)
        epoch0 = pool.epoch_of(idx)
        pid = pool.call("ping", None, sticky=cid)
        os.kill(pid, signal.SIGKILL)
        _wait_until(lambda: pool.crash_count >= 1, what="crash detection")
        assert pool.epoch_of(idx) == epoch0 + 1
        assert crashed == [idx]
        assert not pool.alive()
        # sticky submits to the dead worker fail loudly and immediately
        with pytest.raises(HostWorkerCrash):
            pool.call("ping", None, sticky=cid)
    finally:
        pool.close()


def test_crash_with_restart_respawns_fresh_worker():
    pool = HostPipeline(1, restart_on_crash=True)
    try:
        pid = pool.call("ping", None)
        os.kill(pid, signal.SIGKILL)
        _wait_until(lambda: pool.crash_count >= 1, what="crash detection")
        _wait_until(pool.alive, what="respawn")
        pid2 = pool.call("ping", None)
        assert pid2 != pid
        # the respawned worker has an empty session map: a stale session
        # reads as unknown-channel (auth error), never a desynced decrypt
        with pytest.raises(HostAuthError):
            pool.open_request(b"S" * 16, b"x" * 64, b"")
    finally:
        pool.close()


# -- end-to-end through GrapevineServer --------------------------------


@pytest.fixture(scope="module")
def host_server():
    srv = GrapevineServer(
        CFG, seed=2, max_wait_ms=5.0, clock=lambda: 1_700_000_000,
        host_workers=2, worker_restart=True,
    )
    port = srv.start("insecure-grapevine://127.0.0.1:0")
    yield srv, port
    srv.stop()


def make_client(port, seed_byte):
    c = GrapevineClient(
        f"insecure-grapevine://127.0.0.1:{port}",
        identity_seed=bytes([seed_byte]) * 32,
    )
    c.auth()
    return c


def test_end_to_end_crud_through_pool(host_server):
    srv, port = host_server
    assert srv.hostpipe is not None and srv.scheduler.hostpipe is srv.hostpipe
    alice = make_client(port, 11)
    bob = make_client(port, 12)
    r = alice.create(bob.public_key, pl(b"hello through the pool"))
    assert r.status_code == C.STATUS_CODE_SUCCESS
    r = bob.read()
    assert r.status_code == C.STATUS_CODE_SUCCESS
    assert r.record.payload.startswith(b"hello through the pool")
    assert r.record.sender == alice.public_key
    # lockstep survives a long run of queries through the worker
    for i in range(10):
        assert alice.read().status_code in (
            C.STATUS_CODE_SUCCESS, C.STATUS_CODE_NOT_FOUND
        )
    # sessions carry their sticky worker assignment
    with srv._sessions_lock:
        for s in srv._sessions.values():
            assert s.worker is not None
            assert 0 <= s.worker < 2
    for c in (alice, bob):
        c.close()


def test_bad_signature_rejected_through_pool(host_server):
    """The scheduler's verify fan-out (hostpipe.verify_parallel) must
    reject a garbage challenge signature exactly like the in-process
    MSM: UNAUTHENTICATED, and the session keeps working."""
    import types

    _, port = host_server
    c = make_client(port, 13)
    good_scheme = c._scheme
    c._scheme = types.SimpleNamespace(
        sign=lambda sk, ctx, msg: b"\x00" * C.SIGNATURE_SIZE,
    )
    with pytest.raises(grpc.RpcError) as exc:
        c.read()
    assert exc.value.code() == grpc.StatusCode.UNAUTHENTICATED
    # the challenge WAS consumed (verification happens after the draw,
    # like in-process); with the real scheme back the lockstep matches
    c._scheme = good_scheme
    assert c.read().status_code in (
        C.STATUS_CODE_SUCCESS, C.STATUS_CODE_NOT_FOUND
    )
    c.close()


def test_worker_crash_drops_sessions_and_reauth_recovers(host_server):
    srv, port = host_server
    c = make_client(port, 14)
    assert c.read().status_code in (
        C.STATUS_CODE_SUCCESS, C.STATUS_CODE_NOT_FOUND
    )
    cid = c._channel_id
    idx = srv.hostpipe.worker_for(cid)
    crash0 = srv.hostpipe.crash_count
    pid = srv.hostpipe.call("ping", None, sticky=cid)
    os.kill(pid, signal.SIGKILL)
    _wait_until(lambda: srv.hostpipe.crash_count > crash0,
                what="crash detection")
    # the crash listener dropped every session stuck to that worker
    with srv._sessions_lock:
        assert all(
            s.worker != idx or s.worker_epoch == srv.hostpipe.epoch_of(idx)
            for s in srv._sessions.values()
        )
    with pytest.raises(grpc.RpcError) as exc:
        c.read()
    assert exc.value.code() == grpc.StatusCode.UNAUTHENTICATED
    # worker_restart=True: the pool respawns and a fresh auth serves
    _wait_until(srv.hostpipe.alive, what="respawn")
    c.auth()
    assert c.read().status_code in (
        C.STATUS_CODE_SUCCESS, C.STATUS_CODE_NOT_FOUND
    )
    c.close()


def test_healthz_folds_hostpipe(host_server):
    srv, _ = host_server
    _wait_until(srv.hostpipe.alive, what="pool alive")
    healthy, detail = srv.healthz()
    assert detail["host_workers"] == 2
    assert detail["host_workers_alive"] == 2
    assert healthy


def test_host_telemetry_on_server_registry(host_server):
    srv, _ = host_server
    reg = srv.metrics_registry
    assert reg.get("grapevine_host_workers").get() == 2
    tasks = reg.get("grapevine_host_tasks_total")
    served = sum(
        child.value for _, child in tasks.series()
    )
    assert served > 0
    assert reg.audit()["ok"]
