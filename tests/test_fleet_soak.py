"""Slow-tier fleet uniformity soaks: the discrimination drill from
tests/test_fleet.py re-run with REAL engines behind every shard round
(ISSUE 16 satellite 4's heavy half).

The fast drill proves the detectors' math; these soaks prove the
production wiring — ``ShardRoundDriver.round_fn`` executes a live
``engine_round_step`` per dispatch, so the monitor judges a fleet whose
per-shard round cadence is carried by actual jitted oblivious rounds.
Arrival shapes come from the PR-9 generators (bursty ON/OFF and the
diurnal sinusoid — the two shapes most likely to fool a cadence
detector), recipient-partitioned across shards and binned onto the
shared tick clock. Honest uniform scheduling must PASS under both
(the false-positive budget at fleet grain); the seeded skewed mutant
must SUSPECT within the ISSUE's 64-round bound.

Excluded from the tier-1 gate (-m slow).
"""

from __future__ import annotations

import numpy as np
import pytest

from grapevine_tpu.config import GrapevineConfig
from grapevine_tpu.engine.state import (
    ID_WORDS,
    KEY_WORDS,
    PAYLOAD_WORDS,
    EngineConfig,
    init_engine,
)
from grapevine_tpu.load.generators import (
    bursty_onoff,
    diurnal_sinusoid,
    partition_schedule,
)
from grapevine_tpu.load.harness import ShardRoundDriver
from grapevine_tpu.obs.leakmon import FleetUniformityMonitor

pytestmark = pytest.mark.slow

N_SHARDS = 3
BATCH = 4

SMALL = GrapevineConfig(
    max_messages=64, max_recipients=8, mailbox_cap=4,
    batch_size=BATCH, stash_size=64, bucket_cipher_rounds=0,
)


@pytest.fixture(scope="module")
def fleet_engines():
    """One jitted round step + N independent engine states (same
    geometry, different seeds — shards share a program, never state)."""
    import jax

    from grapevine_tpu.engine.round_step import engine_round_step

    ecfg = EngineConfig.from_config(SMALL)
    step = jax.jit(lambda st, batch: engine_round_step(ecfg, st, batch))
    states = [init_engine(ecfg, seed=100 + i) for i in range(N_SHARDS)]
    # compile once up front so soak timing is steady-state
    states[0], _, _ = step(states[0], _mk_batch(np.random.default_rng(0), 1, BATCH))
    return step, states


def _mk_batch(rng, n_real: int, batch_size: int) -> dict:
    """A CREATE-heavy round batch: n_real live ops + padding NOPs
    (req_type 0), the same shape the production batcher dispatches."""
    req = np.zeros((batch_size,), np.uint32)
    req[:n_real] = 1  # CREATE
    return {
        "req_type": req,
        "auth": rng.integers(
            1, 2**31, (batch_size, KEY_WORDS)).astype(np.uint32),
        "msg_id": np.zeros((batch_size, ID_WORDS), np.uint32),
        "recipient": rng.integers(
            1, 2**31, (batch_size, KEY_WORDS)).astype(np.uint32),
        "payload": rng.integers(
            0, 2**31, (batch_size, PAYLOAD_WORDS)).astype(np.uint32),
        "now": np.uint32(1_700_000_000),
    }


def _live_round_fn(step, states, seed=0):
    import jax

    rng = np.random.default_rng(seed)

    def round_fn(shard: int, n_real: int) -> None:
        states[shard], resp, _t = step(
            states[shard], _mk_batch(rng, n_real, BATCH))
        jax.block_until_ready(resp)

    return round_fn


#: per-shard popularity skew applied on top of the recipient-mod
#: partition: a uniform partition equalizes only EXPECTED load, while
#: real recipient populations are zipf-ish — shard 0 holds the hot
#: mailboxes, shard 2 the cold tail. This asymmetry is what the mutant
#: leaks (its cadence follows it) and simultaneously the honest
#: policy's hardest false-positive case (its cadence must not).
POPULARITY_SKEW = (3.0, 1.0, 0.3)

N_BINS = 64  # tick bins per 40 s schedule (0.625 s ticks)


def _binned_arrivals(schedule):
    """Partition a generator schedule by recipient space, apply the
    popularity skew, and bin each shard's arrival instants onto the
    shared tick clock. Ticks past the schedule wrap (the traffic shape
    repeats) so soaks can outlast one generated window."""
    parts = partition_schedule(schedule, N_SHARDS)
    duration = float(schedule.duration_s)
    counts = [
        np.round(
            np.histogram(p.t_s, bins=N_BINS, range=(0.0, duration))[0] * s
        ).astype(int)
        for p, s in zip(parts, POPULARITY_SKEW)
    ]
    return lambda k: [int(c[k % N_BINS]) for c in counts]


# offered load sits BELOW per-shard drain capacity on purpose: a shard
# whose queue never goes cold dispatches every tick under either
# policy, masking the mutant (an overloaded fleet leaks nothing through
# cadence because there is no idleness to modulate)
ARRIVAL_SHAPES = {
    "bursty": lambda: bursty_onoff(
        rate_on=45.0, duty=0.2, period_s=8.0, duration_s=40.0, seed=21),
    "diurnal": lambda: diurnal_sinusoid(
        mean_rate=15.0, rel_amplitude=0.9, period_s=10.0,
        duration_s=40.0, seed=22),
}

#: bounded-detection budget per shape: the bursty mutant trips within
#: the ISSUE's 64-round bound (long queue-cold OFF runs give the
#: correlation detector its contrast fast); the smooth diurnal ramp
#: yields weaker per-tick evidence, so its bound is one full detector
#: window (128 aligned ticks) — still bounded, just slower, exactly
#: the degraded-evidence semantics OPERATIONS.md §20 documents
MUTANT_TICK_BUDGET = {"bursty": 64, "diurnal": 128}


@pytest.mark.parametrize("shape", sorted(ARRIVAL_SHAPES))
def test_honest_uniform_soak_with_real_engines_passes(
        fleet_engines, shape):
    """The false-positive budget: honest uniform scheduling over live
    engine rounds stays PASS for a full detector window under traffic
    shapes chosen to stress it (per-shard load is allowed to be
    anything; only the SCHEDULE must be uniform)."""
    step, states = fleet_engines
    n_ticks = 160  # > window_ticks: the verdict judges a full window
    mon = FleetUniformityMonitor(N_SHARDS)
    drv = ShardRoundDriver(
        N_SHARDS, mon, policy="uniform", batch_size=BATCH,
        round_fn=_live_round_fn(step, states, seed=31))
    v = drv.run(_binned_arrivals(ARRIVAL_SHAPES[shape]()), n_ticks)
    assert v["verdict"] == "PASS", v
    for det in v["detectors"]:
        assert det["verdict"] == "PASS", det
    # the drill really ran live rounds: every shard committed one per tick
    assert drv.rounds == [n_ticks] * N_SHARDS


@pytest.mark.parametrize("shape", sorted(ARRIVAL_SHAPES))
def test_skewed_mutant_with_real_engines_suspects(fleet_engines, shape):
    """The seeded mutant over live engines: load-gated dispatch must
    flip the fleet verdict within the per-shape tick budget (64 for
    bursty — the ISSUE's bound; one full window for diurnal)."""
    step, states = fleet_engines
    budget = MUTANT_TICK_BUDGET[shape]
    mon = FleetUniformityMonitor(N_SHARDS)
    drv = ShardRoundDriver(
        N_SHARDS, mon, policy="skewed", batch_size=BATCH,
        round_fn=_live_round_fn(step, states, seed=33))
    v = drv.run(_binned_arrivals(ARRIVAL_SHAPES[shape]()), budget,
                stop_on="SUSPECT")
    assert v["verdict"] == "SUSPECT", v
    assert v["ticks"] <= budget
