"""Directed unit suite for the oblivious radix-rank engine.

The primitive contract (oblivious/radix.py): ``radix_rank`` is
bit-identical to ``jnp.argsort(keys, stable=True)`` and
``radix_group_sort`` to ``segmented.multiword_group_sort`` for keys
within their declared bound — stability on duplicates included — and
the declared-bound guard raises on out-of-range concrete keys instead
of silently mis-ranking. Engine-level integration (bit-identical
rounds, zero-sort jaxpr audit) lives in tests/test_sort_radix.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from grapevine_tpu.oblivious.radix import (
    MAX_RADIX_BITS,
    partition_rank,
    radix_group_sort,
    radix_rank,
)
from grapevine_tpu.oblivious.segmented import group_sort, multiword_group_sort

U32 = np.uint32


def _ref(keys):
    return np.asarray(jnp.argsort(jnp.asarray(keys), stable=True))


def _assert_rank_matches(keys, key_bits, bits_per_pass=8):
    got = np.asarray(radix_rank(jnp.asarray(keys), key_bits, bits_per_pass))
    np.testing.assert_array_equal(got, _ref(keys))


def test_stability_on_duplicate_keys():
    """Equal keys must keep original order — the property the eviction
    permutation's bit-identity to the stable argsort rides on."""
    keys = np.array([3, 1, 3, 1, 3, 2, 1, 2, 3, 0], U32)
    for bpp in (1, 2, 8):
        _assert_rank_matches(keys, key_bits=2, bits_per_pass=bpp)
    # heavy duplication: 4 distinct values over 512 slots
    rng = np.random.default_rng(0)
    _assert_rank_matches(rng.integers(0, 4, 512).astype(U32), 2)


def test_all_equal_keys_identity():
    for b in (1, 2, 97):
        got = np.asarray(radix_rank(jnp.full((b,), 5, jnp.uint32), 3))
        np.testing.assert_array_equal(got, np.arange(b))


def test_max_key_saturation():
    """Keys AT the declared bound's ceiling (2^bits - 1) rank correctly
    — the top bin of the last pass."""
    for kb in (1, 7, 8, 21, 32):
        mx = (1 << kb) - 1
        keys = np.array([mx, 0, mx, mx - 1 if kb > 0 else 0, 0, mx], U32)
        _assert_rank_matches(keys, kb)
    # all-saturated
    _assert_rank_matches(np.full(33, (1 << 21) - 1, U32), 21)


def test_key_bits_1_edge():
    rng = np.random.default_rng(1)
    for b in (1, 2, 5, 256):
        keys = rng.integers(0, 2, b).astype(U32)
        for bpp in (1, 8):
            _assert_rank_matches(keys, 1, bpp)


@pytest.mark.slow  # ~27 s: 5 sizes x 6 key widths x 4 pass widths of
# fresh jit compiles. Moved in the PR-9 tier-1 re-budget; the directed
# unit suite above and test_sort_radix's always-on fast campaign keep
# radix_rank equivalence covered in tier-1.
def test_randomized_bounded_draws_match_stable_argsort():
    rng = np.random.default_rng(2)
    for b in (1, 3, 17, 256, 1000):
        for kb in (1, 5, 8, 13, 21, 32):
            hi = ((1 << kb) - 1) if kb < 64 else (1 << 32) - 1
            keys = rng.integers(0, hi + 1, b, dtype=np.uint64).astype(U32)
            for bpp in (1, 5, 8, 11):
                _assert_rank_matches(keys, kb, bpp)


def test_declared_bound_guard_raises_on_out_of_range():
    with pytest.raises(ValueError, match="exceeds the declared"):
        radix_rank(np.array([9], U32), key_bits=3)
    with pytest.raises(ValueError, match="exceeds the declared"):
        radix_group_sort([np.array([0, 1 << 13], U32)], 13)
    # in-range keys at the same width pass
    radix_rank(np.array([7], U32), key_bits=3)


def test_static_parameter_guards():
    k = np.array([0, 1], U32)
    for bad_bits in (0, 33, -1, 8.0, None):
        with pytest.raises(ValueError):
            radix_rank(k, bad_bits)
    for bad_bpp in (0, 17, -3):
        with pytest.raises(ValueError):
            radix_rank(k, 8, bad_bpp)
    with pytest.raises(ValueError):
        radix_group_sort([], 8)
    with pytest.raises(ValueError, match="per column"):
        radix_group_sort([k, k], [8])


def test_wide_key_refusal_not_hashing():
    """radix refuses > MAX_RADIX_BITS declared width — the explicit gate
    that keeps the 256-bit recipient-key sort on lax.sort rather than on
    a hashed-down key (engine/vphases.py)."""
    cols = [np.zeros(4, U32)] * 9
    assert 9 * 32 > MAX_RADIX_BITS
    with pytest.raises(ValueError, match="MAX_RADIX_BITS"):
        radix_group_sort(cols, [32] * 9)


def test_group_sort_radix_matches_multiword():
    rng = np.random.default_rng(3)
    for b in (1, 2, 64, 700):
        cases = [
            ([rng.integers(0, 7, b).astype(U32)], [3]),
            ([rng.integers(0, 1 << 13, b).astype(U32)], [13]),
            (
                [
                    rng.integers(0, 3, b).astype(U32),
                    rng.integers(0, 1 << 11, b).astype(U32),
                ],
                [2, 11],
            ),
        ]
        for cols, bits in cases:
            jc = [jnp.asarray(c) for c in cols]
            ref = multiword_group_sort(jc)
            got = radix_group_sort(jc, bits)
            for r, g in zip(ref, got):
                np.testing.assert_array_equal(np.asarray(r), np.asarray(g))
    # int shorthand for a single column
    c = rng.integers(0, 31, 50).astype(U32)
    ref = multiword_group_sort([jnp.asarray(c)])
    got = radix_group_sort([jnp.asarray(c)], 5)
    for r, g in zip(ref, got):
        np.testing.assert_array_equal(np.asarray(r), np.asarray(g))


def test_segmented_group_sort_knob_bitequal():
    """segmented.group_sort under sort_impl='radix' (the admission
    walk's grouping, engine/vphases.py) equals the stable-argsort path."""
    rng = np.random.default_rng(4)
    for b in (1, 8, 256):
        g = rng.integers(0, max(1, b // 3) + 1, b).astype(U32)
        a = group_sort(jnp.asarray(g))
        r = group_sort(
            jnp.asarray(g), sort_impl="radix",
            key_bits=max(1, (b - 1).bit_length()),
        )
        for x, y in zip(a, r):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_partition_rank_is_the_freelist_formula():
    """partition_rank == the expiry sweep's stable free-first partition
    (engine/expiry.py) == the inverse of radix_rank at key_bits=1."""
    rng = np.random.default_rng(5)
    for n in (1, 2, 100, 1023):
        present = rng.random(n) < 0.4
        pos = np.asarray(partition_rank(jnp.asarray(present)))
        pi = present.astype(np.int64)
        n_free = n - pi.sum()
        ref = np.where(
            present,
            n_free + (np.cumsum(pi) - pi),
            np.cumsum(1 - pi) - (1 - pi),
        )
        np.testing.assert_array_equal(pos, ref)
        # inverse relation: scattering iota at pos gives the stable
        # ascending permutation of the 1-bit keys
        perm = np.asarray(radix_rank(jnp.asarray(present), 1))
        inv = np.zeros(n, np.int64)
        inv[pos] = np.arange(n)
        np.testing.assert_array_equal(perm, inv)


def test_traced_path_skips_concrete_guard():
    """Inside jit the keys are tracers — the declared bound is the
    caller's contract and tracing must not fail (the guard is for the
    eager/test path)."""
    f = jax.jit(lambda k: radix_rank(k, 4))
    out = np.asarray(f(jnp.asarray(np.array([3, 1, 2, 1], U32))))
    np.testing.assert_array_equal(out, [1, 3, 2, 0])
