"""The /metrics + /healthz endpoint (obs/httpd.py) over a live engine
tier: Prometheus exposition includes every round phase, and healthz
flips unhealthy when the engine thread stalls or dies.

Uses the engine tier (server/tier.py EngineServer) rather than the
monolithic server: the endpoint machinery is identical (both route
through start_metrics → obs.MetricsServer), and the engine tier imports
without the session layer's `cryptography` dependency.
"""

import json
import time
import urllib.error
import urllib.request

import pytest

from grapevine_tpu.config import GrapevineConfig
from grapevine_tpu.server.tier import EngineServer
from grapevine_tpu.wire import constants as C
from grapevine_tpu.wire.records import QueryRequest, RequestRecord

NOW = 1_700_000_000


def _req(rt, auth, recipient=C.ZERO_PUBKEY):
    return QueryRequest(
        request_type=rt,
        auth_identity=auth,
        auth_signature=b"\x01" * C.SIGNATURE_SIZE,
        record=RequestRecord(
            msg_id=C.ZERO_MSG_ID,
            recipient=recipient,
            payload=b"\x07" * C.PAYLOAD_SIZE,
        ),
    )


def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:  # 503 still carries a body
        return e.code, e.read().decode()


@pytest.fixture(scope="module")
def tier():
    cfg = GrapevineConfig(
        bucket_cipher_rounds=0,
        max_messages=64,
        max_recipients=16,
        mailbox_cap=4,
        batch_size=4,
        stash_size=96,
        expiry_period=10,
    )
    srv = EngineServer(cfg, seed=7, max_wait_ms=5.0, clock=lambda: NOW)
    port = srv.start_metrics(0, host="127.0.0.1")
    yield srv, port
    srv.stop()


def test_metrics_endpoint_serves_phase_histograms(tier):
    srv, port = tier
    # one authenticated-less round through the real scheduler + engine,
    # plus one expiry sweep, so every phase series has samples
    resp = srv.scheduler.submit(
        _req(C.REQUEST_TYPE_CREATE, bytes([1]) * 32, recipient=bytes([2]) * 32)
    )
    assert resp.status_code == C.STATUS_CODE_SUCCESS
    srv.engine.expire(NOW + 100)

    status, text = _get(f"http://127.0.0.1:{port}/metrics")
    assert status == 200
    # per-phase round histograms (the acceptance set), with samples in
    # the phases this round exercised
    for phase in ("assembly", "verify", "dispatch", "evict", "demux", "sweep"):
        assert f'grapevine_phase_seconds_bucket{{phase="{phase}",le=' in text
    for phase in ("assembly", "dispatch", "evict", "demux", "sweep"):
        assert f'grapevine_phase_seconds_count{{phase="{phase}"}} 0' not in text
    assert "grapevine_rounds_total 1" in text
    assert "grapevine_batch_occupancy 0.25" in text  # 1 real op, B=4
    assert "grapevine_underfull_rounds_total 1" in text
    assert "grapevine_queue_depth " in text
    assert "grapevine_queue_depth_high_water 1" in text
    # the pre-scrape refresh hook sampled the stash (device sync)
    assert "grapevine_stash_high_water" in text
    assert "grapevine_stash_occupancy_count" in text
    assert "grapevine_expiry_sweeps_total 1" in text


def test_merged_health_view_includes_scheduler_and_oram(tier):
    """Satellite: the loopback health dict carries engine counters,
    scheduler gauges, and ORAM stash telemetry in one merged view."""
    srv, _ = tier
    h = srv.health()
    assert "rounds" in h and "messages" in h  # engine
    assert "queue_depth_high_water" in h and "collector_stalls" in h  # sched
    assert "stash_high_water" in h  # ORAM
    assert 'grapevine_phase_seconds{phase=dispatch}_count' in h  # registry


def test_healthz_healthy_then_flips_on_stall_and_death(tier):
    srv, port = tier
    status, body = _get(f"http://127.0.0.1:{port}/healthz")
    assert status == 200 and json.loads(body)["healthy"] is True

    # a wedged engine: the oldest queued op waits past the threshold
    real_stall_age = srv.scheduler.stall_age
    srv.scheduler.stall_age = lambda: 1e9
    try:
        status, body = _get(f"http://127.0.0.1:{port}/healthz")
        assert status == 503 and json.loads(body)["healthy"] is False
    finally:
        srv.scheduler.stall_age = real_stall_age

    status, _ = _get(f"http://127.0.0.1:{port}/healthz")
    assert status == 200

    # a dead collector thread is unhealthy regardless of queue state
    srv.scheduler.close()
    deadline = time.monotonic() + 10
    while srv.scheduler.worker_alive() and time.monotonic() < deadline:
        time.sleep(0.01)
    status, body = _get(f"http://127.0.0.1:{port}/healthz")
    assert status == 503
    assert json.loads(body)["worker_alive"] is False


def test_unknown_path_404(tier):
    _, port = tier
    status, _ = _get(f"http://127.0.0.1:{port}/nope")
    assert status == 404
