"""Tier-1 lint gate (ISSUE 12 satellite; tier promoted in ISSUE 14).

The pinned config lives in pyproject.toml (``[tool.ruff]``, select
E4/E7/E9/F — imports and real errors only, no formatting churn). Where
the ruff binary exists (dev machines, CI images with the wheel) the
gate runs it verbatim; this container bakes its dependencies and ships
no ruff, so the gate falls back to the stdlib AST checker
(grapevine_tpu/analysis/importlint.py — F401 unused imports, F841
unused locals, E722 bare excepts, E9 syntax; polarity chosen to never
false-positive). Either way the suite fails on a real finding; nothing
is installed at test time.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_TARGETS = ["grapevine_tpu", "tools", "tests"]


def test_import_hygiene_gate():
    ruff = shutil.which("ruff")
    if ruff is not None:
        proc = subprocess.run(
            [ruff, "check", *_TARGETS], cwd=REPO,
            capture_output=True, text=True,
        )
        assert proc.returncode == 0, (
            f"ruff check failed:\n{proc.stdout}\n{proc.stderr}"
        )
        return
    from grapevine_tpu.analysis.importlint import check_tree

    findings = {}
    for target in _TARGETS:
        for rel, items in check_tree(os.path.join(REPO, target)).items():
            findings[os.path.join(target, rel)] = items
    assert not findings, f"unused imports (F401): {findings}"


def test_importlint_detects_seeded_finding():
    """Positive control: the fallback has teeth."""
    from grapevine_tpu.analysis.importlint import check_source

    src = "import os\nimport sys\n\nprint(sys.argv)\n"
    findings = check_source(src)
    assert [(ln, name) for ln, name, _ in findings] == [(1, "os")]
    # noqa and __init__ semantics: a marked line is exempt
    assert check_source("import os  # noqa: F401\n") == []
    # syntax errors surface instead of passing silently (the E9 subset)
    assert check_source("def broken(:\n")[0][1] == "<syntax>"


def test_importlint_f841_unused_local():
    """The ISSUE-14 tier promotion: F841 with conservative scoping."""
    from grapevine_tpu.analysis.importlint import check_source

    flagged = check_source(
        "def f():\n    x = compute()\n    return 1\n"
    )
    assert [(n) for _, n, _ in flagged] == ["x"]
    # used, underscore, closure-read, and noqa'd bindings stay clean
    assert check_source("def f():\n    x = 1\n    return x\n") == []
    assert check_source("def f():\n    _scratch = 1\n    return 2\n") == []
    assert check_source(
        "def f():\n    x = 1\n    def g():\n        return x\n"
        "    return g\n"
    ) == []
    assert check_source(
        "def f():\n    x = compute()  # noqa: F841\n    return 1\n"
    ) == []
    # dynamic scopes (locals/eval) disable the check for that function
    assert check_source(
        "def f():\n    x = 1\n    return locals()\n"
    ) == []
    # an augmented assignment READS the prior binding — never flagged
    # (review finding: `x = 0; x += 1` must not suggest deleting x = 0)
    assert check_source(
        "def f():\n    x = 0\n    x += 1\n    return 2\n"
    ) == []
    # `except ... as e` with an unread name is the other F841 shape
    flagged = check_source(
        "def f():\n    try:\n        g()\n"
        "    except ValueError as exc:\n        pass\n"
    )
    assert [(n) for _, n, _ in flagged] == ["exc"]


def test_importlint_e722_bare_except():
    from grapevine_tpu.analysis.importlint import check_source

    flagged = check_source(
        "def f():\n    try:\n        g()\n    except:\n        pass\n"
    )
    assert [(n) for _, n, _ in flagged] == ["<bare-except>"]
    assert check_source(
        "def f():\n    try:\n        g()\n"
        "    except Exception:\n        pass\n"
    ) == []
    assert check_source(
        "def f():\n    try:\n        g()\n"
        "    except:  # noqa: E722\n        pass\n"
    ) == []


def test_fallback_matches_package_clean_state():
    """The package itself is lint-clean through the fallback — the
    state the satellite fix left it in (5 unused imports removed)."""
    from grapevine_tpu.analysis.importlint import check_tree

    assert check_tree(os.path.join(REPO, "grapevine_tpu")) == {}


if __name__ == "__main__":
    sys.exit(subprocess.call(
        [sys.executable, "-m", "pytest", __file__, "-q"]
    ))
