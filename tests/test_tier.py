"""Split frontend/engine tier (server/tier.py): N session-termination
processes sharing ONE device engine — the horizontal host-path
architecture PERF.md's 1M ops/s budget relies on. In-process here
(separate gRPC servers on loopback), process-separated in deployment;
the wire between tiers is identical either way."""

from __future__ import annotations

import grpc
import pytest

from grapevine_tpu.config import GrapevineConfig
from grapevine_tpu.server.client import GrapevineClient
from grapevine_tpu.server.tier import ENGINE_SERVICE_NAME, EngineServer, FrontendServer
from grapevine_tpu.wire import constants as C


@pytest.fixture(scope="module")
def tier():
    cfg = GrapevineConfig(
        max_messages=256, max_recipients=32, batch_size=8,
        bucket_cipher_rounds=0,
    )
    engine = EngineServer(cfg, seed=5)
    eport = engine.start("127.0.0.1:0")
    fe_a = FrontendServer(f"127.0.0.1:{eport}", config=cfg)
    fe_b = FrontendServer(f"127.0.0.1:{eport}", config=cfg)
    pa = fe_a.start("insecure-grapevine://127.0.0.1:0")
    pb = fe_b.start("insecure-grapevine://127.0.0.1:0")
    yield {"engine": engine, "eport": eport, "pa": pa, "pb": pb}
    fe_a.stop()
    fe_b.stop()
    engine.stop()


def test_cross_frontend_crud(tier):
    """Alice on frontend A, Bob on frontend B, one engine: the full
    CRUD contract holds across the tier split."""
    alice = GrapevineClient(
        f"insecure-grapevine://127.0.0.1:{tier['pa']}", identity_seed=b"\x41" * 32
    )
    bob = GrapevineClient(
        f"insecure-grapevine://127.0.0.1:{tier['pb']}", identity_seed=b"\x42" * 32
    )
    alice.auth()
    bob.auth()
    payload = b"tiered".ljust(C.PAYLOAD_SIZE, b"\x00")
    r1 = alice.create(bob.public_key, payload)
    assert r1.status_code == C.STATUS_CODE_SUCCESS
    r2 = bob.read(msg_id=r1.record.msg_id)
    assert r2.status_code == C.STATUS_CODE_SUCCESS
    assert r2.record.payload == payload
    assert r2.record.sender == alice.public_key
    r3 = bob.delete(msg_id=r1.record.msg_id, recipient=bob.public_key)
    assert r3.status_code == C.STATUS_CODE_SUCCESS
    r4 = alice.read(msg_id=r1.record.msg_id)
    assert r4.status_code == C.STATUS_CODE_NOT_FOUND


def test_forged_signature_rejected_at_engine(tier):
    """The sr25519 check lives in the ENGINE tier: a frontend session
    whose client signs garbage gets UNAUTHENTICATED end to end."""
    mallory = GrapevineClient(
        f"insecure-grapevine://127.0.0.1:{tier['pa']}", identity_seed=b"\x66" * 32
    )
    mallory.auth()
    scheme = mallory._scheme

    class Forged:
        keygen = staticmethod(scheme.keygen)

        @staticmethod
        def sign(sk, ctx, msg):
            return b"\x01" * 63 + b"\x81"  # marked, bogus

    mallory._scheme = Forged
    try:
        with pytest.raises(grpc.RpcError) as ei:
            mallory.create(b"\x05" * 32, b"\x00" * C.PAYLOAD_SIZE)
        assert ei.value.code() == grpc.StatusCode.UNAUTHENTICATED
    finally:
        mallory._scheme = scheme
    # the session survives? No: the lockstep challenge advanced on both
    # sides (draw happens before verification), so the NEXT request
    # still verifies — same behavior as the monolithic server.
    r = mallory.create(b"\x05" * 32, b"\x01" * C.PAYLOAD_SIZE)
    assert r.status_code == C.STATUS_CODE_SUCCESS


def test_engine_rejects_malformed_submit(tier):
    """Direct internal-API misuse fails closed (size + decode checks)."""
    chan = grpc.insecure_channel(f"127.0.0.1:{tier['eport']}")
    identity = lambda b: b  # noqa: E731
    submit = chan.unary_unary(
        f"/{ENGINE_SERVICE_NAME}/Submit",
        request_serializer=identity, response_deserializer=identity,
    )
    for bad in (b"", b"\x00" * 10, b"\xff" * (C.QUERY_REQUEST_WIRE_SIZE + 31)):
        with pytest.raises(grpc.RpcError) as ei:
            submit(bad)
        assert ei.value.code() == grpc.StatusCode.INVALID_ARGUMENT
    chan.close()


def test_rounds_batch_across_frontends(tier):
    """Ops arriving via different frontends share engine rounds: the
    round counter grows by less than one round per op under concurrent
    cross-frontend load (quiescence batching at the engine)."""
    import threading

    eng = tier["engine"].engine
    rounds0 = eng.metrics.snapshot()["rounds"]
    clients = []
    for i, port in ((0, tier["pa"]), (1, tier["pb"]), (2, tier["pa"]), (3, tier["pb"])):
        c = GrapevineClient(
            f"insecure-grapevine://127.0.0.1:{port}",
            identity_seed=bytes([0x70 + i]) * 32,
        )
        c.auth()
        clients.append(c)
    n_each = 6
    errs = []

    def run(c):
        try:
            for j in range(n_each):
                r = c.create(c.public_key, bytes([j]) * C.PAYLOAD_SIZE)
                assert r.status_code == C.STATUS_CODE_SUCCESS
        except Exception as e:  # pragma: no cover
            errs.append(e)

    ts = [threading.Thread(target=run, args=(c,)) for c in clients]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs
    n_ops = n_each * len(clients)
    rounds = eng.metrics.snapshot()["rounds"] - rounds0
    assert 0 < rounds < n_ops, (rounds, n_ops)


def test_engine_submit_fuzz_fail_closed(tier):
    """Random and mutated submissions to the internal API must fail
    closed (INVALID_ARGUMENT / UNAUTHENTICATED), never crash the engine
    tier or commit an op."""
    import os
    import random

    eng = tier["engine"].engine
    msgs0 = eng.message_count()
    chan = grpc.insecure_channel(f"127.0.0.1:{tier['eport']}")
    identity = lambda b: b  # noqa: E731
    submit = chan.unary_unary(
        f"/{ENGINE_SERVICE_NAME}/Submit",
        request_serializer=identity, response_deserializer=identity,
    )
    rng = random.Random(99)
    right_size = C.QUERY_REQUEST_WIRE_SIZE + C.CHALLENGE_SIZE
    for i in range(40):
        kind = rng.randrange(3)
        if kind == 0:  # random bytes, random length
            data = os.urandom(rng.randrange(0, right_size * 2))
        elif kind == 1:  # right length, random content (bad sig/type)
            data = os.urandom(right_size)
        else:  # right length, zeroed (invalid request type)
            data = bytes(right_size)
        try:
            submit(data, timeout=10)  # a hang must fail, not wedge pytest
        except grpc.RpcError as e:
            assert e.code() in (
                grpc.StatusCode.INVALID_ARGUMENT,
                grpc.StatusCode.UNAUTHENTICATED,
            ), (i, e.code())
        else:  # pragma: no cover - would mean a forged op committed
            raise AssertionError(f"fuzz case {i} was accepted")
    assert eng.message_count() == msgs0  # nothing committed
    chan.close()


def test_engine_tier_runs_expiry_sweep():
    """The engine tier owns the device, so it owns the expiry sweep
    (the same run_expiry_loop the monolithic server uses)."""
    import time

    cfg = GrapevineConfig(
        max_messages=64, max_recipients=16, batch_size=4,
        bucket_cipher_rounds=0, expiry_period=10,
    )
    now = [1_700_000_000]
    engine = EngineServer(cfg, seed=9, clock=lambda: now[0])
    eport = engine.start("127.0.0.1:0")
    fe = FrontendServer(f"127.0.0.1:{eport}", config=cfg)
    port = fe.start("insecure-grapevine://127.0.0.1:0")
    try:
        c = GrapevineClient(
            f"insecure-grapevine://127.0.0.1:{port}", identity_seed=b"\x77" * 32
        )
        c.auth()
        r = c.create(c.public_key, b"\x05" * C.PAYLOAD_SIZE)
        assert r.status_code == C.STATUS_CODE_SUCCESS
        assert engine.engine.message_count() == 1
        now[0] += 1000  # all records now older than the period
        deadline = time.time() + 15  # sweep interval = period/10 = 1 s
        while engine.engine.message_count() and time.time() < deadline:
            time.sleep(0.25)
        assert engine.engine.message_count() == 0, "sweep never evicted"
    finally:
        fe.stop()
        engine.stop()
