"""Role/flag validation of the server CLI (server/cli.py): misapplied
flags fail loudly by argv-token presence, even at default values."""

from __future__ import annotations

import pytest

from grapevine_tpu.server import cli


def _check(argv):
    parser = cli.build_parser()
    args = parser.parse_args(argv)
    cli._reject_misapplied_flags(parser, args, argv)
    return args


@pytest.mark.parametrize("argv", [
    ["--role", "engine", "--identity-seed", "ab" * 32],
    ["--role", "engine", "--tls-cert", "c.pem"],
    # explicitly supplied WITH the default value still rejects
    ["--role", "engine", "--listen", "insecure-grapevine://0.0.0.0:3229"],
    ["--role", "frontend", "--seed", "0"],
    ["--role", "frontend", "--expiry-period", "60"],
    ["--role", "mono", "--engine", "x:1"],
    ["--role", "mono", "--engine-listen", "127.0.0.1:0"],
    # observability flags observe the device round: frontend rejects
    # them even at their default values (ISSUE 6 satellite)
    ["--role", "frontend", "--trace-ring-size", "512"],
    ["--role", "frontend", "--slo-commit-p99-ms", "250.0"],
    ["--role", "frontend", "--profile-enable"],
    # engine geometry lives with the device: a frontend supplying
    # --posmap-impl would silently configure nothing (ISSUE 7 satellite)
    ["--role", "frontend", "--posmap-impl", "recursive"],
    ["--role", "frontend", "--posmap-impl", "flat"],
    # same for the tree-top cache depth (ISSUE 8 satellite) — rejected
    # even at the explicit "off" value
    ["--role", "frontend", "--tree-top-cache-levels", "4"],
    ["--role", "frontend", "--tree-top-cache-levels", "0"],
    # the round pipeline runs on the device-owning role (ISSUE 10
    # satellite) — rejected even at the explicit serial value
    ["--role", "frontend", "--pipeline-depth", "2"],
    ["--role", "frontend", "--pipeline-depth", "1"],
    # eviction deferral is engine geometry (ISSUE 15 satellite) — a
    # frontend supplying it would silently defer nothing; rejected even
    # at the explicit per-round value, and the buffer override with it
    ["--role", "frontend", "--evict-every", "4"],
    ["--role", "frontend", "--evict-every", "1"],
    ["--role", "frontend", "--evict-buffer-slots", "4096"],
    # the bucket-tree shard count is engine geometry (ISSUE 18): a
    # frontend supplying it would silently shard nothing — rejected
    # even at the explicit single-chip value, and on the fleet role
    ["--role", "frontend", "--shards", "2"],
    ["--role", "frontend", "--shards", "1"],
    ["--role", "fleet", "--fleet-members", "h0:1", "--shards", "2"],
    # fleet topology/cadence belongs to the fleet role alone (ISSUE 16
    # satellite): any other role supplying --fleet-* would silently
    # aggregate nothing — rejected even at default values
    ["--role", "mono", "--fleet-members", "h0:1,h1:1"],
    ["--role", "engine", "--fleet-members", "h0:1"],
    ["--role", "frontend", "--fleet-members", "h0:1"],
    ["--role", "mono", "--fleet-scrape-interval", "1.0"],
    ["--role", "engine", "--fleet-scrape-interval", "0.5"],
    ["--role", "frontend", "--fleet-port", "0"],
    ["--role", "engine", "--fleet-port", "9500"],
    # ...and the fleet role owns no device, listener, or sessions: it
    # rejects engine/frontend/mono flags, even at default values
    ["--role", "fleet", "--fleet-members", "h0:1", "--batch-size", "8"],
    ["--role", "fleet", "--fleet-members", "h0:1", "--listen",
     "insecure-grapevine://0.0.0.0:3229"],
    ["--role", "fleet", "--fleet-members", "h0:1", "--state-dir", "/x"],
    ["--role", "fleet", "--fleet-members", "h0:1", "--leakmon"],
    ["--role", "fleet", "--fleet-members", "h0:1", "--engine", "x:1"],
    ["--role", "fleet", "--fleet-members", "h0:1",
     "--metrics-port", "9464"],
    ["--role", "fleet", "--fleet-members", "h0:1", "--seed", "0"],
    # journal shipping needs the journal in-process (ISSUE 19): a
    # frontend supplying --replicate-to would silently replicate
    # nothing (its journal lives in the engine tier) — rejected even
    # at the --ship-every default; the fleet owns no journal either
    ["--role", "frontend", "--replicate-to", "127.0.0.1:4100"],
    ["--role", "frontend", "--ship-every", "1"],
    ["--role", "fleet", "--fleet-members", "h0:1",
     "--replicate-to", "127.0.0.1:4100"],
    # ...and the standby's own surface belongs to the standby role
    # alone: any other role supplying --standby-listen or
    # --promote-from would silently stand nothing by — rejected even
    # at default values
    ["--role", "mono", "--standby-listen", "127.0.0.1:0"],
    ["--role", "engine", "--standby-listen", "127.0.0.1:0"],
    ["--role", "frontend", "--standby-listen", "127.0.0.1:0"],
    ["--role", "mono", "--promote-from", "/var/lib/grapevine"],
    ["--role", "engine", "--promote-from", "/var/lib/grapevine"],
    # the standby is the replication TARGET: it takes no client-facing
    # listener, no --replicate-to chain, no fleet topology
    ["--role", "standby", "--state-dir", "/x",
     "--replicate-to", "127.0.0.1:4100"],
    ["--role", "standby", "--state-dir", "/x", "--listen",
     "insecure-grapevine://0.0.0.0:3229"],
    ["--role", "standby", "--state-dir", "/x", "--identity-seed",
     "ab" * 32],
    ["--role", "standby", "--state-dir", "/x",
     "--fleet-members", "h0:1"],
    # the host pipeline terminates sessions (mono, frontend) or
    # verifies rounds (engine); the fleet aggregator and the
    # pre-promotion standby touch neither (ISSUE 20)
    ["--role", "fleet", "--fleet-members", "h0:1", "--host-workers", "2"],
    ["--role", "standby", "--state-dir", "/x", "--host-workers", "2"],
    # adaptive/flush-aware collection shapes the device round window —
    # a frontend supplying it would silently shape nothing (its rounds
    # are collected in the engine tier)
    ["--role", "frontend", "--engine", "h:1", "--adaptive-batch"],
    ["--role", "frontend", "--engine", "h:1", "--flush-window", "4"],
    ["--role", "fleet", "--fleet-members", "h0:1", "--adaptive-batch"],
])
def test_misapplied_flags_rejected(argv):
    with pytest.raises(SystemExit, match="does not take"):
        _check(argv)


@pytest.mark.parametrize("argv", [
    [],
    ["--role", "mono", "--listen", "insecure-grapevine://0.0.0.0:1",
     "--identity-seed", "ab" * 32, "--expiry-period", "60"],
    ["--role", "engine", "--engine-listen", "127.0.0.1:0",
     "--msg-capacity", "512", "--batch-size", "16", "--seed", "3"],
    ["--role", "frontend", "--engine", "127.0.0.1:4000",
     "--listen", "insecure-grapevine://0.0.0.0:1", "--batch-size", "16"],
    # the metrics endpoint is a per-process concern: every role takes it
    ["--metrics-port", "9464"],
    ["--role", "engine", "--engine-listen", "127.0.0.1:0",
     "--metrics-port", "9464"],
    ["--role", "frontend", "--engine", "127.0.0.1:4000",
     "--metrics-port", "0"],
    # device-owning roles take the tracer/SLO/profiler flags
    ["--role", "mono", "--trace-ring-size", "1024",
     "--slo-commit-p99-ms", "100", "--profile-enable"],
    ["--role", "engine", "--engine-listen", "127.0.0.1:0",
     "--trace-ring-size", "64", "--slo-commit-p99-ms", "500.5",
     "--profile-enable"],
    # device-owning roles take the position-map knob (ISSUE 7)
    ["--role", "mono", "--posmap-impl", "recursive"],
    ["--role", "engine", "--engine-listen", "127.0.0.1:0",
     "--posmap-impl", "flat"],
    # …and the tree-top cache depth (ISSUE 8)
    ["--role", "mono", "--tree-top-cache-levels", "4"],
    ["--role", "engine", "--engine-listen", "127.0.0.1:0",
     "--tree-top-cache-levels", "0"],
    # …and the round-pipeline depth (ISSUE 10)
    ["--role", "mono", "--pipeline-depth", "2"],
    ["--role", "engine", "--engine-listen", "127.0.0.1:0",
     "--pipeline-depth", "1"],
    # …and the eviction-deferral cadence + buffer override (ISSUE 15)
    ["--role", "mono", "--evict-every", "4"],
    ["--role", "engine", "--engine-listen", "127.0.0.1:0",
     "--evict-every", "1"],
    ["--role", "mono", "--evict-every", "4",
     "--evict-buffer-slots", "4096"],
    # …and the bucket-tree shard count, alone and composed with the
    # eviction cadence — the ISSUE-18 pairing (sharded E>1 flush)
    ["--role", "mono", "--shards", "2"],
    ["--role", "engine", "--engine-listen", "127.0.0.1:0",
     "--shards", "4", "--evict-every", "4"],
    ["--role", "mono", "--shards", "1"],
    # the fleet role takes its topology/cadence flags + the bind
    # interface (ISSUE 16)
    ["--role", "fleet", "--fleet-members", "127.0.0.1:9464,127.0.0.1:9465"],
    ["--role", "fleet", "--fleet-members", "h0:1,h1:1",
     "--fleet-scrape-interval", "0.25", "--fleet-port", "0"],
    ["--role", "fleet", "--fleet-members", "h0:1",
     "--metrics-host", "127.0.0.1", "-v"],
    # device-owning roles ship their journal to a standby (ISSUE 19),
    # alone and with the shipping cadence knob
    ["--role", "mono", "--state-dir", "/x",
     "--replicate-to", "127.0.0.1:4100"],
    ["--role", "engine", "--engine-listen", "127.0.0.1:0",
     "--state-dir", "/x", "--replicate-to", "127.0.0.1:4100",
     "--ship-every", "4"],
    # the standby role: its feed listener, the primary dir it fences
    # at promotion, durability + geometry (it replays into a real
    # engine), and the engine listener it serves on after promotion
    ["--role", "standby", "--state-dir", "/x"],
    ["--role", "standby", "--state-dir", "/x",
     "--standby-listen", "127.0.0.1:0",
     "--promote-from", "/var/lib/grapevine",
     "--engine-listen", "127.0.0.1:0"],
    ["--role", "standby", "--state-dir", "/x", "--evict-every", "4",
     "--pipeline-depth", "1", "--tree-top-cache-levels", "0",
     "--metrics-port", "0"],
    # the host pipeline + adaptive/flush knobs (ISSUE 20): every
    # session-terminating or round-verifying role takes --host-workers;
    # the frontend also takes --worker-restart (hostpipe crash policy,
    # no durability implied); adaptive windows belong to roles owning
    # a BatchScheduler over an in-process engine (mono/engine/standby)
    ["--role", "mono", "--host-workers", "2", "--adaptive-batch",
     "--flush-window", "4"],
    ["--role", "engine", "--engine-listen", "127.0.0.1:0",
     "--host-workers", "2", "--adaptive-batch"],
    ["--role", "frontend", "--engine", "127.0.0.1:4000",
     "--host-workers", "2", "--worker-restart"],
    ["--role", "standby", "--state-dir", "/x", "--adaptive-batch",
     "--flush-window", "4"],
])
def test_valid_role_flag_combinations_accepted(argv):
    _check(argv)  # must not raise


def test_abbreviated_options_rejected():
    """allow_abbrev=False: the presence scan matches exact tokens, so
    abbreviations must not parse at all."""
    with pytest.raises(SystemExit):
        cli.build_parser().parse_args(["--rol", "engine"])


def test_unclaimed_parser_flag_fails_loudly(monkeypatch):
    """A flag added to build_parser but missing from every role's set
    must error at validation time (and not via a strippable assert)."""
    trimmed = {k: v - {"seed"} for k, v in cli._ROLE_FLAGS.items()}
    monkeypatch.setattr(cli, "_ROLE_FLAGS", trimmed)
    with pytest.raises(SystemExit, match="missing from _ROLE_FLAGS"):
        _check([])
