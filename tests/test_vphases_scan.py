"""Dense-vs-scan vphases equivalence: bit-identical engines, no [B,B].

The tentpole contract of the scan slot-order machinery
(engine/vphases.py, ``vphases_impl="scan"``):

1. responses AND final engine state bit-identical to the dense impl —
   randomized oracle campaigns over op mixes heavy in same-key chains,
   zero-id pops, saturation-fallback rounds, and single-op batches
   (the same contract the cipher impls carry, testing/compare.py);
2. the scan impl's jaxpr materializes NO [B,B]-shaped intermediate at
   B=256 (asserted on the traced jaxpr, with the dense impl as the
   positive control proving the checker sees such intermediates).

The fast campaign count keeps tier-1 within budget; the full ≥200-
campaign sweep runs under ``-m slow`` (and was run at PR time — see
PERF.md Round 6). Set $GRAPEVINE_VPHASES_CAMPAIGNS to override.
"""

import functools
import os
import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from grapevine_tpu.config import GrapevineConfig
from grapevine_tpu.engine.batcher import GrapevineEngine
from grapevine_tpu.engine.round_step import engine_round_step
from grapevine_tpu.engine.state import (
    EngineConfig,
    ID_WORDS,
    KEY_WORDS,
    PAYLOAD_WORDS,
    init_engine,
)
from grapevine_tpu.testing.reference import ReferenceEngine
from grapevine_tpu.wire import constants as C
from grapevine_tpu.wire.records import QueryRequest, RequestRecord

NOW = 1_700_000_000

BASE = dict(
    bucket_cipher_rounds=0,
    max_messages=64,
    max_recipients=8,
    mailbox_cap=4,
    batch_size=8,
    stash_size=96,
)
#: bus within B of full from the start (free_top < B after one round of
#: creates) — every later round takes the _admission_slow lax.scan
#: branch; mailbox_cap raised so the bus quota binds before the
#: per-recipient cap
SAT_BUS = dict(BASE, max_messages=16, mailbox_cap=16)
#: recipient table can never cover a full batch (recipients0 + B > max)
#: — the slow branch runs from round one
SAT_RECIP = dict(BASE, max_recipients=4)


def key(n: int) -> bytes:
    return bytes([n & 0xFF, (n >> 8) ^ 0x5A]) + b"\x01" * 30


def req(rt, auth, msg_id=C.ZERO_MSG_ID, recipient=C.ZERO_PUBKEY, tag=0):
    return QueryRequest(
        request_type=rt,
        auth_identity=auth,
        auth_signature=b"\x01" * C.SIGNATURE_SIZE,
        record=RequestRecord(
            msg_id=msg_id,
            recipient=recipient,
            payload=bytes([tag & 0xFF]) * C.PAYLOAD_SIZE,
        ),
    )


def _mk_pair(cfg_kwargs, seed):
    dense = GrapevineEngine(
        GrapevineConfig(vphases_impl="dense", **cfg_kwargs), seed=seed
    )
    scan = GrapevineEngine(
        GrapevineConfig(vphases_impl="scan", **cfg_kwargs), seed=seed
    )
    return dense, scan


def _assert_responses_bitequal(rd, rs, ctx=""):
    for j, (d, s) in enumerate(zip(rd, rs)):
        assert d.status_code == s.status_code, f"{ctx} slot {j}: status"
        assert d.record.msg_id == s.record.msg_id, f"{ctx} slot {j}: id"
        assert d.record.sender == s.record.sender, f"{ctx} slot {j}: sender"
        assert d.record.recipient == s.record.recipient, f"{ctx} slot {j}"
        assert d.record.timestamp == s.record.timestamp, f"{ctx} slot {j}: ts"
        assert d.record.payload == s.record.payload, f"{ctx} slot {j}: payload"


def _assert_states_bitequal(ea, eb, ctx=""):
    la = jax.tree_util.tree_leaves_with_path(ea.state)
    lb = jax.tree_util.tree_leaves(eb.state)
    for (path, x), y in zip(la, lb):
        assert np.array_equal(np.asarray(x), np.asarray(y)), (
            f"{ctx}: state diverges at {jax.tree_util.keystr(path)}"
        )


def _gen_batch(rng, idents, live_ids, n):
    """Op mix heavy in same-key chains and zero-id pops; explicit-id
    R/U/D drawn from live ids (stale ids → NOT_FOUND, also exercised)."""
    reqs = []
    for _ in range(n):
        r = rng.random()
        a = idents[rng.integers(len(idents))]
        x = idents[rng.integers(len(idents))]
        if r < 0.30:
            reqs.append(
                req(C.REQUEST_TYPE_CREATE, a, recipient=x,
                    tag=int(rng.integers(256)))
            )
        elif r < 0.34:  # zero recipient → INVALID_RECIPIENT
            reqs.append(req(C.REQUEST_TYPE_CREATE, a))
        elif r < 0.55:
            reqs.append(req(C.REQUEST_TYPE_READ, a))  # zero-id pop-read
        elif r < 0.72:
            reqs.append(req(C.REQUEST_TYPE_DELETE, a))  # zero-id pop
        elif live_ids and r < 0.82:
            mid, owner = live_ids[rng.integers(len(live_ids))]
            reqs.append(req(C.REQUEST_TYPE_READ, a, msg_id=mid))
        elif live_ids and r < 0.92:
            mid, owner = live_ids[rng.integers(len(live_ids))]
            rcp = owner if rng.random() < 0.7 else x
            reqs.append(
                req(C.REQUEST_TYPE_UPDATE, owner, msg_id=mid, recipient=rcp,
                    tag=int(rng.integers(256)))
            )
        elif live_ids:
            mid, owner = live_ids[rng.integers(len(live_ids))]
            reqs.append(
                req(C.REQUEST_TYPE_DELETE, owner, msg_id=mid, recipient=owner)
            )
        else:
            reqs.append(req(C.REQUEST_TYPE_READ, x))
    return reqs


def _run_campaign(cfg_kwargs, seed, n_batches=3, batch_fill=None,
                  mk_pair=None):
    """One campaign: a fresh engine A/B pair + oracle, mixed batches.

    Asserts pair ≡ bitwise (responses, then final state) and both
    ≡ oracle semantics (forced-id comparison, counts included).
    ``mk_pair`` builds the (a, b) engines under test — default the
    dense/scan vphases pair; tests/test_sort_radix.py reuses the whole
    campaign with an xla/radix sort pair instead.
    """
    rng = np.random.default_rng(seed)
    dense, scan = (mk_pair or _mk_pair)(
        cfg_kwargs, seed=int(rng.integers(1 << 30))
    )
    oracle = ReferenceEngine(
        config=GrapevineConfig(**cfg_kwargs), rng=random.Random(seed)
    )
    idents = [key(i) for i in range(1, 1 + int(rng.integers(2, 6)))]
    live_ids: list[tuple[bytes, bytes]] = []
    bs = cfg_kwargs["batch_size"]
    for bi in range(n_batches):
        n = batch_fill or int(rng.integers(1, bs + 1))
        reqs = _gen_batch(rng, idents, live_ids, n)
        t = NOW + bi
        rd = dense.handle_queries(reqs, t)
        rs = scan.handle_queries(reqs, t)
        _assert_responses_bitequal(rd, rs, f"seed {seed} batch {bi}")
        forced = [
            d.record.msg_id
            if r.request_type == C.REQUEST_TYPE_CREATE
            and d.status_code == C.STATUS_CODE_SUCCESS
            else None
            for r, d in zip(reqs, rd)
        ]
        ro = oracle.handle_batch(reqs, t, forced)
        for j, (r, d, o) in enumerate(zip(reqs, rd, ro)):
            assert d.status_code == o.status_code, (
                f"seed {seed} batch {bi} slot {j}: engine "
                f"{d.status_code} != oracle {o.status_code}"
            )
            assert d.record.msg_id == o.record.msg_id
            assert d.record.payload == o.record.payload
            assert d.record.timestamp == o.record.timestamp
        assert dense.message_count() == oracle.message_count()
        assert dense.recipient_count() == oracle.recipient_count()
        for r, d in zip(reqs, rd):
            if (
                r.request_type == C.REQUEST_TYPE_CREATE
                and d.status_code == C.STATUS_CODE_SUCCESS
            ):
                live_ids.append((d.record.msg_id, r.record.recipient))
            elif (
                r.request_type == C.REQUEST_TYPE_DELETE
                and d.status_code == C.STATUS_CODE_SUCCESS
            ):
                live_ids = [
                    (m, o_) for m, o_ in live_ids if m != d.record.msg_id
                ]
    _assert_states_bitequal(dense, scan, f"seed {seed}")


def _campaign_plan(n_total):
    """Distribute campaigns over the regimes; every regime represented."""
    plans = []
    for i in range(n_total):
        r = i % 10
        if r < 5:
            plans.append((BASE, None))  # steady-state fast path
        elif r < 7:
            plans.append((SAT_BUS, None))  # bus saturation fallback
        elif r < 9:
            plans.append((SAT_RECIP, None))  # recipient-table fallback
        else:
            plans.append((BASE, 1))  # single-op batches (dummy-padded)
    return plans


_FAST_N = int(os.environ.get("GRAPEVINE_VPHASES_CAMPAIGNS", "8"))


def test_randomized_ab_campaigns():
    """Budget-shaped fast set: the cost is ~all jit compiles (one per
    distinct geometry × impl), so the fast plan spans two geometries —
    steady-state and bus-saturation. Both saturation regimes resolve
    through the same _admission_slow scan (only the tripping guard
    differs), so bus-saturation keeps the fallback branch covered; the
    recipient-table geometry runs in the -m slow full sweep."""
    for i, (cfg, fill) in enumerate(_campaign_plan(_FAST_N)):
        if cfg is SAT_RECIP:
            cfg = SAT_BUS
        _run_campaign(cfg, seed=1000 + i, batch_fill=fill)


@pytest.mark.slow
def test_randomized_ab_campaigns_full():
    """The full ≥200-campaign acceptance sweep (run at PR time; kept
    under -m slow so tier-1 stays within its budget)."""
    for i, (cfg, fill) in enumerate(_campaign_plan(220)):
        _run_campaign(cfg, seed=5000 + i, batch_fill=fill)


@pytest.mark.slow  # two extra engine compiles (~15 s); the B=1 segment
# edge cases are covered always-on by the segmented property tests and
# the fill=1 campaigns in the fast plan
def test_single_op_batch_engine_ab():
    """batch_size=1 end to end: the sort/scan machinery at B=1 (segment
    logic degenerate cases) stays bit-identical and oracle-true."""
    cfg = dict(BASE, batch_size=1)
    for i in range(6):
        _run_campaign(cfg, seed=300 + i, n_batches=6, batch_fill=1)


def test_saturation_fallback_engaged_and_bitequal():
    """Drive the bus to saturation so fast_ok is False (free_top < B):
    rounds resolve through _admission_slow under both impls and must
    stay bit-identical, including TOO_MANY_MESSAGES admission order."""
    dense, scan = _mk_pair(SAT_BUS, seed=9)
    a, x = key(1), key(2)
    t = NOW
    # 3 full batches of creates against max_messages=16: round 2 onward
    # runs with free_top < B=8 → the lax.scan branch
    for bi in range(3):
        reqs = [
            req(C.REQUEST_TYPE_CREATE, a, recipient=x, tag=bi * 8 + j)
            for j in range(8)
        ]
        rd = dense.handle_queries(reqs, t + bi)
        rs = scan.handle_queries(reqs, t + bi)
        _assert_responses_bitequal(rd, rs, f"sat batch {bi}")
    assert dense.message_count() <= 16
    codes = {r.status_code for r in rd}
    assert C.STATUS_CODE_TOO_MANY_MESSAGES in codes  # quota actually hit
    _assert_states_bitequal(dense, scan, "saturation")


# ----------------------------------------------------------------------
# jaxpr shape audit: the scan impl materializes no [B,B] intermediate
# ----------------------------------------------------------------------

JAXPR_B = 256


def _iter_jaxprs(jaxpr):
    yield jaxpr
    for eqn in jaxpr.eqns:
        for v in eqn.params.values():
            vs = v if isinstance(v, (list, tuple)) else (v,)
            for x in vs:
                inner = getattr(x, "jaxpr", None)
                if inner is not None and hasattr(inner, "eqns"):
                    yield from _iter_jaxprs(inner)
                elif hasattr(x, "eqns"):
                    yield from _iter_jaxprs(x)


def _quadratic_avals(jaxpr, b):
    """All bool/f32 avals in the jaxpr with ≥2 axes of extent ≥ b.

    Record values are u32[B, 256] at the 1KB record size — exactly B
    words wide at B=256 — so a ``jnp.where(mask[:, None], rows, ...)``
    over record rows carries a broadcast bool predicate of shape
    (B, 256) that is batch×value-width, not a same-key matrix. Those
    two representational primitives (the predicate broadcast and the
    select it feeds) are excluded for bools; every *computational* use
    of a genuine [B,B] mask (and/or/reduce/convert, and the f32 one-hot
    matmul operands) remains audited, which the dense positive-control
    test proves is sufficient to detect the dense impl.
    """
    bad = []
    skip_bool = ("select_n", "broadcast_in_dim")
    for jx in _iter_jaxprs(jaxpr):
        for eqn in jx.eqns:
            for var in list(eqn.invars) + list(eqn.outvars):
                aval = getattr(var, "aval", None)
                shape = getattr(aval, "shape", ())
                dtype = getattr(aval, "dtype", None)
                if dtype is None:
                    continue
                if dtype not in (jnp.bool_, jnp.float32):
                    continue
                if dtype == jnp.bool_ and eqn.primitive.name in skip_bool:
                    continue
                if sum(1 for dim in shape if dim >= b) >= 2:
                    bad.append((eqn.primitive.name, str(dtype), tuple(shape)))
    return bad


def _trace_engine_jaxpr(impl):
    cfg = GrapevineConfig(
        max_messages=1 << 12,
        max_recipients=1 << 8,
        mailbox_cap=4,
        batch_size=JAXPR_B,
        bucket_cipher_rounds=0,
        stash_size=512,
        vphases_impl=impl,
    )
    ecfg = EngineConfig.from_config(cfg)
    state = jax.eval_shape(lambda: init_engine(ecfg, 0))
    b = JAXPR_B
    u32 = jnp.uint32
    batch = {
        "req_type": jax.ShapeDtypeStruct((b,), u32),
        "auth": jax.ShapeDtypeStruct((b, KEY_WORDS), u32),
        "msg_id": jax.ShapeDtypeStruct((b, ID_WORDS), u32),
        "recipient": jax.ShapeDtypeStruct((b, KEY_WORDS), u32),
        "payload": jax.ShapeDtypeStruct((b, PAYLOAD_WORDS), u32),
        "now": jax.ShapeDtypeStruct((), u32),
        "now_hi": jax.ShapeDtypeStruct((), u32),
    }
    return jax.make_jaxpr(functools.partial(engine_round_step, ecfg))(
        state, batch
    ).jaxpr


def test_scan_jaxpr_has_no_quadratic_intermediate():
    bad = _quadratic_avals(_trace_engine_jaxpr("scan"), JAXPR_B)
    assert not bad, (
        f"scan impl materializes quadratic mask intermediates at "
        f"B={JAXPR_B}: {sorted(set(bad))[:8]}"
    )


def test_dense_jaxpr_audit_positive_control():
    """The dense impl DOES materialize [B,B] masks — proving the audit
    actually detects the intermediates the scan test asserts away."""
    bad = _quadratic_avals(_trace_engine_jaxpr("dense"), JAXPR_B)
    assert bad, "audit found no [B,B] intermediates even in the dense impl"


def test_vphases_impl_knob_validation():
    with pytest.raises(ValueError):
        GrapevineConfig(vphases_impl="bogus")
    # None resolves per backend at engine-config time; tests force CPU
    ecfg = EngineConfig.from_config(GrapevineConfig())
    assert ecfg.vphases_impl == "scan"
    assert (
        EngineConfig.from_config(
            GrapevineConfig(vphases_impl="dense")
        ).vphases_impl
        == "dense"
    )
