"""Telemetry leak audit (obs/registry.py): the allowlist has teeth, the
shipped registry is batch-level only, and the CI policy checker agrees.

The telemetry counterpart of test_leak_canary.py: those tests prove the
transcript detectors catch deliberately-leaky engines; these prove the
registry rejects deliberately-leaky *metrics* — per-client / per-op
label keys, undeclared label values, mutable bucket boundaries.
"""

import importlib.util
import os

import pytest

from grapevine_tpu.engine.metrics import EngineMetrics
from grapevine_tpu.obs import (
    ALLOWED_LABEL_KEYS,
    FORBIDDEN_LABEL_KEYS,
    TelemetryLeakError,
    TelemetryRegistry,
    render_prometheus,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- registration-time rejection ---------------------------------------


@pytest.mark.parametrize("key", ["op_type", "client_id", "msg_id", "recipient"])
def test_forbidden_label_key_raises_at_registration(key):
    reg = TelemetryRegistry()
    with pytest.raises(TelemetryLeakError, match="side channel|allowlist"):
        reg.counter("grapevine_bad_total", "nope", labels={key: ("x",)})


def test_unallowlisted_key_raises_even_if_not_explicitly_forbidden():
    reg = TelemetryRegistry()
    with pytest.raises(TelemetryLeakError, match="allowlist"):
        reg.gauge("grapevine_bad", "nope", labels={"color": ("red",)})


def test_label_values_must_be_declared():
    reg = TelemetryRegistry()
    with pytest.raises(TelemetryLeakError, match="no values"):
        reg.counter("grapevine_bad_total", "nope", labels={"phase": ()})


def test_undeclared_label_value_raises_at_sample_time():
    reg = TelemetryRegistry()
    h = reg.histogram(
        "grapevine_x_seconds", "x", buckets=(0.1, 1.0),
        labels={"phase": ("verify",)},
    )
    h.observe(0.5, phase="verify")  # declared: fine
    with pytest.raises(TelemetryLeakError, match="not.*declared|dynamic"):
        # a session token smuggled through a *safe* key is still a leak
        h.observe(0.5, phase="deadbeef")


def test_histogram_buckets_fixed_and_sorted():
    reg = TelemetryRegistry()
    with pytest.raises(ValueError, match="increasing"):
        reg.histogram("grapevine_h_seconds", "h", buckets=(1.0, 0.5))
    with pytest.raises(ValueError, match="increasing"):
        reg.histogram("grapevine_h2_seconds", "h", buckets=())


def test_duplicate_metric_name_raises():
    reg = TelemetryRegistry()
    reg.counter("grapevine_a_total", "a")
    with pytest.raises(ValueError, match="duplicate"):
        reg.counter("grapevine_a_total", "again")


def test_forbidden_and_allowed_sets_disjoint():
    assert not (ALLOWED_LABEL_KEYS & FORBIDDEN_LABEL_KEYS)


# -- the audit over the shipped registry -------------------------------


def test_shipped_registry_passes_audit():
    report = EngineMetrics().registry.audit()
    assert report["ok"] and report["metrics"] >= 10


def test_audit_catches_smuggled_series():
    """A series injected past the public API (simulating a bug) fails
    the audit even though registration-time checks never saw it."""
    m = EngineMetrics()
    counter = m.registry.get("grapevine_rounds_total")
    from grapevine_tpu.obs.registry import _CounterChild

    counter._children[("deadbeef",)] = _CounterChild()
    with pytest.raises(TelemetryLeakError, match="undeclared series"):
        m.registry.audit()


def test_telemetry_policy_checker_clean():
    """The CI gate (tools/check_telemetry_policy.py) passes on the tree
    as shipped: no forbidden label keys at any instrumentation call
    site, and the shipped registry audits clean. Unmarked on purpose —
    it rides the tier-1 ``-m 'not slow'`` run, so a policy regression
    fails CI fast."""
    path = os.path.join(REPO, "tools", "check_telemetry_policy.py")
    spec = importlib.util.spec_from_file_location("check_telemetry_policy", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.scan_call_sites() == []
    assert mod.audit_shipped_registry()["ok"]


def test_host_namespace_audit_and_teeth():
    """The host serving pipeline's namespace audit (ISSUE-20 satellite):
    ``audit_host_registry`` builds the real HostPipeline + adaptive
    policy + flush-windowed scheduler against one registry and passes —
    and the teeth it relies on bite here directly: a channel-id-valued
    ``worker`` label (the exact identity the sticky channel→worker
    routing could be tempted to export) raises TelemetryLeakError at
    registration, as does a ``channel_id`` label key."""
    path = os.path.join(REPO, "tools", "check_telemetry_policy.py")
    spec = importlib.util.spec_from_file_location(
        "check_telemetry_policy_host", path
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    report = mod.audit_host_registry()
    assert report["ok"] and report["host_families"] >= 9

    reg = TelemetryRegistry()
    with pytest.raises(TelemetryLeakError):
        reg.counter(
            "grapevine_host_tasks_total", "t",
            labels={"worker": ("deadbeef" * 4,)},
        )
    with pytest.raises(TelemetryLeakError):
        reg.counter(
            "grapevine_host_tasks_total", "t",
            labels={"channel_id": ("0",)},
        )


# -- exposition format -------------------------------------------------


def test_prometheus_render_format():
    reg = TelemetryRegistry()
    c = reg.counter("grapevine_ops_total", "ops")
    c.inc(3)
    h = reg.histogram(
        "grapevine_t_seconds", "t", buckets=(0.1, 1.0),
        labels={"phase": ("verify", "dispatch")},
    )
    h.observe(0.05, phase="verify")
    h.observe(0.5, phase="verify")
    h.observe(2.0, phase="verify")
    text = render_prometheus(reg)
    assert "# TYPE grapevine_ops_total counter" in text
    assert "grapevine_ops_total 3" in text
    # cumulative buckets: le="0.1" 1, le="1" 2, +Inf == count == 3
    assert 'grapevine_t_seconds_bucket{phase="verify",le="0.1"} 1' in text
    assert 'grapevine_t_seconds_bucket{phase="verify",le="1"} 2' in text
    assert 'grapevine_t_seconds_bucket{phase="verify",le="+Inf"} 3' in text
    assert 'grapevine_t_seconds_count{phase="verify"} 3' in text
    # the undriven series exists with zero samples (stable scrape schema)
    assert 'grapevine_t_seconds_count{phase="dispatch"} 0' in text


def test_prometheus_escaping_per_0_0_4():
    """ISSUE 2 satellite: HELP text escapes ``\\`` and newlines; label
    values escape ``\\``, ``"``, and newlines — a declared value with a
    quote must not corrupt the series name for everything after it."""
    reg = TelemetryRegistry()
    g = reg.gauge(
        "grapevine_esc_test",
        'help with \\ backslash\nand "newline" line',
        labels={"phase": ('va"l\\ue\nx', "plain")},
    )
    g.set(1.0, phase='va"l\\ue\nx')
    text = render_prometheus(reg)
    assert (
        "# HELP grapevine_esc_test "
        'help with \\\\ backslash\\nand "newline" line'
    ) in text
    assert 'grapevine_esc_test{phase="va\\"l\\\\ue\\nx"} 1' in text
    # every line still parses as comment-or-sample (no raw newlines
    # smuggled mid-line)
    for line in text.splitlines():
        assert line.startswith("#") or " " in line


def test_leakmon_gauges_under_registry_policy():
    """The leakmon namespace registers through the same audited
    registry: tree-labeled aggregates only, audit() clean."""
    from grapevine_tpu.obs.flightrec import FlightRecorder
    from grapevine_tpu.obs.leakmon import EngineLeakMonitor

    em = EngineMetrics()
    mon = EngineLeakMonitor(
        mb_leaves=16, rec_leaves=128, mb_choices=2,
        registry=em.registry, recorder=FlightRecorder(capacity=8),
    )
    try:
        report = em.registry.audit()
        assert report["ok"]
        fams = [m.name for m in em.registry.collect()
                if m.name.startswith("grapevine_leakmon_")]
        assert "grapevine_leakmon_samekey_collision_rate" in fams
        assert "grapevine_leakmon_cross_round_repeat_rate" in fams
        assert "grapevine_leakmon_uniformity_z" in fams
        assert "grapevine_leakmon_suspect" in fams
        for m in em.registry.collect():
            if m.name.startswith("grapevine_leakmon_"):
                assert set(m.label_keys) <= {"tree"}
    finally:
        mon.close()
