"""Columnar pack_batch / unpack_responses: exact field layout + edges.

The engine-vs-oracle suites cover these paths end to end; this file
pins the codec contract directly (field byte layout, padding, n=0,
over-capacity) so a layout regression fails with a precise message
rather than a downstream semantic mismatch.
"""

import numpy as np
import pytest

from grapevine_tpu.engine.batcher import pack_batch, unpack_responses
from grapevine_tpu.engine.state import ID_WORDS, KEY_WORDS, PAYLOAD_WORDS
from grapevine_tpu.testing.fixtures import get_seeded_rng, random_query_request
from grapevine_tpu.wire import constants as C

NOW = 1_700_000_000


def test_pack_roundtrips_every_field_and_pads():
    rng = get_seeded_rng(5)
    reqs = [random_query_request(rng) for _ in range(5)]
    batch = pack_batch(reqs, 8, NOW)
    assert batch["req_type"].shape == (8,)
    assert batch["auth"].shape == (8, KEY_WORDS)
    assert batch["msg_id"].shape == (8, ID_WORDS)
    assert batch["payload"].shape == (8, PAYLOAD_WORDS)
    for i, r in enumerate(reqs):
        assert int(batch["req_type"][i]) == r.request_type
        assert batch["auth"][i].tobytes() == r.auth_identity
        assert batch["msg_id"][i].tobytes() == r.record.msg_id
        assert batch["recipient"][i].tobytes() == r.record.recipient
        assert batch["payload"][i].tobytes() == r.record.payload
    # padding slots are all-zero dummies (request_type 0)
    for i in range(5, 8):
        assert int(batch["req_type"][i]) == 0
        assert not batch["auth"][i].any()
        assert not batch["payload"][i].any()
    assert int(batch["now"]) == NOW


def test_pack_empty_and_overfull():
    batch = pack_batch([], 4, NOW)
    assert not batch["req_type"].any()
    rng = get_seeded_rng(6)
    with pytest.raises(ValueError):
        pack_batch([random_query_request(rng) for _ in range(5)], 4, NOW)


def test_unpack_slices_rows_correctly():
    b = 6
    resp = {
        "status": np.arange(1, b + 1, dtype=np.uint32),
        "msg_id": np.arange(b * ID_WORDS, dtype=np.uint32).reshape(b, ID_WORDS),
        "sender": np.arange(b * KEY_WORDS, dtype=np.uint32).reshape(b, KEY_WORDS),
        "recipient": np.arange(b * KEY_WORDS, dtype=np.uint32).reshape(b, KEY_WORDS) + 7,
        # u64 lanes: (lo, hi); hi exercises the 2106+ range
        "timestamp": np.stack(
            [np.arange(b, dtype=np.uint32) + 100,
             np.full(b, 2, dtype=np.uint32)], axis=1
        ),
        "payload": np.arange(b * PAYLOAD_WORDS, dtype=np.uint32).reshape(b, PAYLOAD_WORDS),
    }
    out = unpack_responses(resp, 4)  # fewer than the device batch
    assert len(out) == 4
    for i, q in enumerate(out):
        assert q.status_code == i + 1
        assert q.record.timestamp == (2 << 32) + 100 + i
        assert q.record.msg_id == resp["msg_id"][i].astype("<u4").tobytes()
        assert q.record.sender == resp["sender"][i].astype("<u4").tobytes()
        assert q.record.recipient == resp["recipient"][i].astype("<u4").tobytes()
        assert q.record.payload == resp["payload"][i].astype("<u4").tobytes()
        assert len(q.record.msg_id) == C.MSG_ID_SIZE
        assert len(q.record.payload) == C.PAYLOAD_SIZE
