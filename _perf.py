import sys, time
from functools import partial
import numpy as np, jax, jax.numpy as jnp
from grapevine_tpu.config import GrapevineConfig
from grapevine_tpu.engine.state import EngineConfig, init_engine
from grapevine_tpu.engine.round_step import engine_round_step
from bench import make_batches

cap, bs, reps = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
cfg = GrapevineConfig(max_messages=cap, max_recipients=1 << 12,
                      batch_size=bs, stash_size=max(224, bs // 2 + 96))
ecfg = EngineConfig.from_config(cfg)
state = init_engine(ecfg, seed=0)
raw = make_batches(8, bs)
stacked = {k: jnp.stack([jnp.asarray(b[k]) for b in raw]) for k in raw[0]}

@partial(jax.jit, static_argnums=(2,))
def many_rounds(state, stacked, reps):
    def outer(st, _):
        def body(st, batch):
            st, resp, _ = engine_round_step(ecfg, st, batch)
            return st, resp["status"].sum()
        st, s = jax.lax.scan(body, st, stacked)
        return st, s.sum()
    state, sums = jax.lax.scan(outer, state, None, length=reps)
    return state, sums.sum() + state.rec.tree_val.sum() + state.mb.tree_val.sum()

st2, c = many_rounds(state, stacked, reps)
_ = int(np.asarray(c))  # compile + settle
t0 = time.perf_counter()
st2, c = many_rounds(state, stacked, reps)
cval = int(np.asarray(c))
dt = time.perf_counter() - t0
rounds = 8 * reps
ov = int(np.asarray(st2.rec.overflow)) + int(np.asarray(st2.mb.overflow))
print(f"cap=2^{cap.bit_length()-1} bs={bs}: {dt/rounds*1e3:.3f} ms/round, {bs*rounds/dt:,.0f} ops/s, ov={ov}")
