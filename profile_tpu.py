#!/usr/bin/env python3
"""Capture a JAX profiler trace of the engine round on real TPU.

PERF.md lever 1: replace the analytic ~5-10 ms/round cost model with a
trace-backed attribution. Run on a host with a working TPU backend:

    python profile_tpu.py [--impl jnp|pallas|pallas_fused]
                          [--cap-log2 20] [--batch 2048] [--rounds 8]
                          [--outdir /tmp/grapevine-trace]

Prints one JSON line with per-round wall time and writes a perfetto/
tensorboard trace directory. View: tensorboard --logdir <outdir>, or
upload trace.json.gz to ui.perfetto.dev.

Deliberately NOT part of bench.py: the profiler adds overhead and the
trace directory is an artifact to inspect, not a scoreboard number.
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import time


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--impl", default="jnp",
                    choices=["jnp", "pallas", "pallas_fused"])
    ap.add_argument("--cap-log2", type=int, default=20)
    ap.add_argument("--batch", type=int, default=2048)
    ap.add_argument("--rounds", type=int, default=8)
    ap.add_argument("--outdir", default="/tmp/grapevine-trace")
    args = ap.parse_args()

    import jax

    from grapevine_tpu.config import TPU_BACKENDS

    backend = jax.default_backend()
    if backend not in TPU_BACKENDS:
        print(json.dumps({"error": f"needs a TPU backend, have {backend!r}"}))
        return 1

    import bench

    cap = 1 << args.cap_log2
    cfg, ecfg, state, step = bench._mk_engine(
        cap, 1 << 12, args.batch, cipher_impl=args.impl
    )
    batches = bench.make_batches(4, args.batch)
    # compile + settle outside the trace window
    state, resp, _ = step(ecfg, state, batches[0])
    jax.block_until_ready(resp)

    times = []
    with jax.profiler.trace(args.outdir):
        for i in range(args.rounds):
            t0 = time.perf_counter()
            state, resp, _ = step(ecfg, state, batches[i % 4])
            jax.block_until_ready(resp)
            times.append(time.perf_counter() - t0)
    per_round_ms = statistics.median(times) * 1e3
    print(json.dumps({
        "impl": args.impl,
        "capacity_log2": args.cap_log2,
        "batch": args.batch,
        "median_round_ms": round(per_round_ms, 3),
        "ops_per_sec_blocking": round(args.batch / (per_round_ms / 1e3), 1),
        "trace_dir": args.outdir,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
