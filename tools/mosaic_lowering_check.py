#!/usr/bin/env python3
"""Cross-platform Mosaic lowering check for the three Pallas kernels.

Mosaic's BlockSpec/tiling constraints are enforced at LOWERING time,
not at execution — so ``jax.export`` with ``platforms=('tpu',)`` runs
the full Pallas→Mosaic lowering pipeline on a CPU-only host and
reproduces exactly the class of error the first real TPU window
surfaced (TPURUN_r5.jsonl mosaic stage: rank-1 block size 86 not a
multiple of the 128-lane tile). This cannot prove the kernels RUN
(VMEM fit and Mosaic compile proper happen on-device), but it proves
the lowering contract the window rejected.

Shapes checked are the real engine geometries, taken from the same
configs the TPU capture's mosaic stage and the bench's headline config
instantiate:
  - records tree rows: z=4 slot words + 4 slots x 255 value words
  - mailbox rows: two-choice table rows (engine/vphases.py)
plus the exact (172-row, nb=24) case that failed on the first window.

Run:  JAX_PLATFORMS=cpu python tools/mosaic_lowering_check.py
Exit code 0 = every kernel lowers for TPU.
"""

from __future__ import annotations

import functools
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

# the axon PJRT sitecustomize overrides JAX_PLATFORMS via jax.config, so
# pin through jax.config too (same workaround as tests/conftest.py) —
# otherwise this checker initializes the tunneled TPU backend and blocks
# whenever another process holds the single-claim relay
jax.config.update("jax_platforms", "cpu")

U32 = jnp.uint32


def _lower_tpu(fn, *args, **static):
    """jax.export against an abstract TPU mesh: runs Mosaic lowering."""
    from jax import export

    wrapped = jax.jit(functools.partial(fn, **static))
    specs = [
        jax.ShapeDtypeStruct(a.shape, a.dtype) if hasattr(a, "shape") else a
        for a in args
    ]
    export.export(wrapped, platforms=("tpu",))(*specs)


def check_cipher(r, z, vw):
    from grapevine_tpu.oblivious.pallas_cipher import cipher_rows_pallas

    key = jnp.zeros((8,), U32)
    bucket = jnp.zeros((r,), U32)
    epoch = jnp.zeros((r, 2), U32)
    pidx = jnp.zeros((r, z), U32)
    pval = jnp.zeros((r, vw), U32)
    _lower_tpu(cipher_rows_pallas, key, bucket, epoch, pidx, pval,
               rounds=8, interpret=False)


def check_gather(n, r, z, v, tiled=False):
    from grapevine_tpu.oblivious.pallas_gather import (
        gather_decrypt_rows,
        gather_decrypt_rows_tiled,
    )

    key = jnp.zeros((8,), U32)
    tree_idx = jnp.zeros((n * z,), U32)
    tree_val = jnp.zeros((n, z * v), U32)
    nonces = jnp.zeros((n, 2), U32)
    flat_b = jnp.zeros((r,), U32)
    fn = gather_decrypt_rows_tiled if tiled else gather_decrypt_rows
    _lower_tpu(fn, key, tree_idx, tree_val, nonces,
               flat_b, z=z, rounds=8, interpret=False)


def check_scatter(n, r, z, v, tiled=False):
    from grapevine_tpu.oblivious.pallas_gather import (
        scatter_encrypt_rows,
        scatter_encrypt_rows_tiled,
    )

    key = jnp.zeros((8,), U32)
    tree_idx = jnp.zeros((n * z,), U32)
    tree_val = jnp.zeros((n, z * v), U32)
    nonces = jnp.zeros((n, 2), U32)
    flat_b = jnp.zeros((r,), U32)
    owner = jnp.zeros((r,), jnp.bool_)
    epoch = jnp.zeros((2,), U32)
    new_pidx = jnp.zeros((r, z), U32)
    new_pval = jnp.zeros((r, z * v), U32)
    fn = scatter_encrypt_rows_tiled if tiled else scatter_encrypt_rows
    _lower_tpu(fn, key, tree_idx, tree_val, nonces,
               flat_b, owner, epoch, new_pidx, new_pval, z=z, rounds=8,
               interpret=False)


CASES = [
    # (name, thunk) — geometries from the engine's two trees at the
    # capture/bench configs, plus the exact first-window failure shape
    ("cipher records r=172 (failed on TPU window 1)",
     lambda: check_cipher(172, 4, 380)),
    ("cipher records B=2048-ish path set",
     lambda: check_cipher(40960, 4, 1020 - 4)),
    ("cipher mailbox rows", lambda: check_cipher(352, 4, 60)),
    ("cipher tiny (cap 2^6 smoke)", lambda: check_cipher(14, 4, 1016)),
    ("gather records", lambda: check_gather(2048, 1320, 4, 254)),
    ("gather tiny", lambda: check_gather(65, 22, 4, 254)),
    ("scatter records", lambda: check_scatter(2048, 1320, 4, 254)),
    ("scatter tiny", lambda: check_scatter(65, 22, 4, 254)),
    ("gather tiled records",
     lambda: check_gather(2048, 1320, 4, 254, tiled=True)),
    ("gather tiled tiny", lambda: check_gather(65, 22, 4, 254, tiled=True)),
    ("scatter tiled records",
     lambda: check_scatter(2048, 1320, 4, 254, tiled=True)),
    ("scatter tiled tiny",
     lambda: check_scatter(65, 22, 4, 254, tiled=True)),
]


def main():
    bad = 0
    for name, thunk in CASES:
        try:
            thunk()
            print(f"OK    {name}")
        except Exception as e:  # noqa: BLE001 — report-all checker
            bad += 1
            msg = str(e).split("\n")[0][:300]
            print(f"FAIL  {name}: {type(e).__name__}: {msg}")
    print(f"{len(CASES) - bad}/{len(CASES)} kernels lower for TPU")
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
