#!/usr/bin/env python
"""Crash-recovery chaos harness (the PR-4 acceptance gate).

Runs a deterministic engine workload in a child process with durability
on, SIGKILLs the child at a randomized point — either an armed
fault-injection site inside the journal/checkpoint protocol
(testing/faults.py) or a random wall-clock timer — restarts it until the
workload completes, and asserts the run is **bit-identical** to an
uninterrupted oracle:

- every per-round response hash the (possibly many) child incarnations
  recorded matches the oracle's hash for that round;
- the final recovered engine state equals the oracle's final state,
  byte for byte (engine/checkpoint.py's canonical serialization);
- the leak monitor verdict stays PASS on the recovered engine
  (obliviousness survives recovery);
- no run ever half-loads a torn checkpoint or journal file (a child
  incarnation failing with anything but SIGKILL fails the trial).

Usage::

    JAX_PLATFORMS=cpu python tools/chaos_run.py --trials 50
    JAX_PLATFORMS=cpu python tools/chaos_run.py --points   # one trial
                                                           # per fault site
    JAX_PLATFORMS=cpu python tools/chaos_run.py --standby --points
        # hot-standby mode: SIGKILL the primary once at every fault
        # site and verify the promoted replica instead of a restart

The child re-enters this file with ``--child``; a shared JAX persistent
compilation cache keeps relaunches from re-paying the compile.
"""

from __future__ import annotations

import argparse
import hashlib
import os
import random
import shutil
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

NOW0 = 1_700_000_000
ENGINE_SEED = 3
SWEEP_PERIOD = 10_000
MAX_RESTARTS = 60


def _config(posmap_impl: str | None = None,
            tree_top_cache_levels: int | None = None,
            pipeline_depth: int | None = None,
            evict_every: int | None = None,
            shards: int | None = None):
    from grapevine_tpu.config import GrapevineConfig

    return GrapevineConfig(
        max_messages=64, max_recipients=8, mailbox_cap=4,
        batch_size=4, stash_size=64, bucket_cipher_rounds=0,
        posmap_impl=posmap_impl,
        tree_top_cache_levels=tree_top_cache_levels,
        pipeline_depth=pipeline_depth,
        evict_every=evict_every,
        shards=shards or 1,
    )


def _key(n: int) -> bytes:
    return bytes([n & 0xFF, (n >> 8) & 0xFF, n ^ 0x5A]) + b"\x01" * 29


def build_schedule(seed: int, n_events: int):
    """Deterministic event list; event i carries journal seq i+1.

    Requests avoid response-derived inputs (zero-id READ/DELETE pops
    instead of id lookups) so the schedule is a pure function of the
    seed — any incarnation of the child reconstructs it identically."""
    from grapevine_tpu.wire import constants as C
    from grapevine_tpu.wire.records import QueryRequest, RequestRecord

    rng = random.Random(seed)
    events = []
    for i in range(n_events):
        if i % 7 == 5:
            events.append(("sweep", NOW0 + i, SWEEP_PERIOD))
            continue
        reqs = []
        for _ in range(rng.randrange(1, 5)):
            c = rng.random()
            if c < 0.6:
                rt, rcp = C.REQUEST_TYPE_CREATE, _key(rng.randrange(1, 6))
            elif c < 0.9:
                rt, rcp = C.REQUEST_TYPE_READ, C.ZERO_PUBKEY
            else:
                rt, rcp = C.REQUEST_TYPE_DELETE, C.ZERO_PUBKEY
            reqs.append(QueryRequest(
                request_type=rt,
                auth_identity=_key(rng.randrange(1, 6)),
                auth_signature=b"\x01" * C.SIGNATURE_SIZE,
                record=RequestRecord(
                    msg_id=C.ZERO_MSG_ID,
                    recipient=rcp,
                    payload=bytes([rng.randrange(256)]) * C.PAYLOAD_SIZE,
                ),
            ))
        events.append(("round", NOW0 + i, reqs))
    return events


def _resp_hash(resps) -> str:
    return hashlib.sha256(b"".join(r.pack() for r in resps)).hexdigest()


def _events_done(events, durable_seq: int, evict_every: int) -> int:
    """Events covered by the durable journal prefix.

    At evict_every=1 journal seq IS the event count (the original
    identity). At E>1 every E-th round appends a KIND_FLUSH frame of
    its own, so the mapping is seq(n) = n + floor(rounds(n)/E) —
    walked forward here. Recovery completes a pending flush before the
    child reads ``durability.seq`` (engine/batcher.py), so the durable
    seq always lands on an event boundary; anything else is journal
    corruption and must raise, never silently re-run or skip events."""
    if evict_every <= 1:
        return durable_seq
    seq = rounds = 0
    if seq == durable_seq:
        return 0
    for n, ev in enumerate(events):
        seq += 1  # the event's own frame
        if ev[0] == "round":
            rounds += 1
            if rounds % evict_every == 0:
                seq += 1  # its flush frame
        if seq == durable_seq:
            return n + 1
    raise RuntimeError(
        f"durable journal seq {durable_seq} does not land on an event "
        f"boundary of the {len(events)}-event schedule at "
        f"evict_every={evict_every}"
    )


def _run_events(engine, events, start: int, progress=None):
    """Drive ``events[start:]``; append ``seq hash`` progress lines.

    Pipelined per the engine's resolved ``pipeline_depth``: up to depth
    rounds stay dispatched-but-unresolved ACROSS events (the engine's
    async path with a bounded ledger — the scheduler's discipline), so
    the journal/dispatch crash sites fire while earlier rounds are
    genuinely mid-flight on the device. Rounds resolve oldest-first (=
    dispatch = journal order); a crash loses only the progress lines of
    rounds that never resolved, whose recovery the final-state hash
    still fully covers. Depth 1 keeps the ledger empty at every event
    boundary — the serial pre-PR-10 program, bit for bit."""
    depth = max(1, getattr(engine, "pipeline_depth", 1))
    ledger: list = []  # (event seq, PendingRound) in dispatch order

    def settle_one():
        seq, pending = ledger.pop(0)
        h = _resp_hash(pending.resolve())
        if progress is not None:
            progress.write(f"{seq} {h}\n")
            progress.flush()

    for i in range(start, len(events)):
        ev = events[i]
        # the pipeline bound: at depth d, dispatch (or sweep — it runs
        # synchronously under the same engine lock) with at most d-1
        # rounds already in flight
        while len(ledger) > depth - 1:
            settle_one()
        if ev[0] == "round":
            ledger.append(
                (i + 1, engine.handle_queries_async(ev[2], ev[1]))
            )
        else:
            engine.expire(ev[1], period=ev[2])
            if progress is not None:
                progress.write(f"{i + 1} sweep\n")
                progress.flush()
    while ledger:
        settle_one()


def run_child(args) -> int:
    from grapevine_tpu.config import DurabilityConfig
    from grapevine_tpu.engine.batcher import GrapevineEngine
    from grapevine_tpu.engine.checkpoint import state_to_bytes
    from grapevine_tpu.obs.leakmon import EngineLeakMonitor, LeakMonitorConfig

    dcfg = DurabilityConfig(
        state_dir=args.state_dir,
        checkpoint_every_rounds=args.checkpoint_every,
        journal_fsync_every=1,
    )
    engine = GrapevineEngine(
        _config(args.posmap_impl, args.tree_top_cache_levels,
                args.pipeline_depth, args.evict_every, args.shards),
        seed=ENGINE_SEED, durability=dcfg,
    )
    shipper = None
    if args.replicate_to:
        # hot-standby chaos (--standby): this child is the PRIMARY,
        # streaming every sealed frame to the parent's replica until
        # the armed fault SIGKILLs it mid-protocol
        from grapevine_tpu.engine.replication import JournalShipper

        shipper = JournalShipper(engine, args.replicate_to)
        shipper.start()
    monitor = EngineLeakMonitor.for_engine(
        engine, LeakMonitorConfig(window_rounds=64)
    )
    engine.attach_leakmon(monitor)
    if shipper is not None:
        monitor.attach_shipper(shipper)
    # the PR-6 observability stack rides every chaos incarnation (as it
    # does in serving): tracing/SLO must never perturb recovery
    # bit-equality, and the tracer's schema check runs on real
    # journal/checkpoint-bearing ledgers here
    from grapevine_tpu.obs.slo import SloTracker
    from grapevine_tpu.obs.tracer import RoundTracer

    engine.attach_tracer(
        RoundTracer(capacity=64, registry=engine.metrics.registry)
    )
    engine.attach_slo(SloTracker(registry=engine.metrics.registry))
    events = build_schedule(args.schedule_seed, args.events)
    # events[:start] are already durable (flush frames excluded from
    # the count — they are cadence bookkeeping, not schedule events)
    start = _events_done(events, engine.durability.seq, engine.evict_every)
    with open(args.progress, "a") as pf:
        _run_events(engine, events, start, pf)
        monitor.close()  # drain the detector queue before the verdict
        verdict = monitor.verdict()["verdict"]
        final = hashlib.sha256(
            state_to_bytes(engine.ecfg, engine.state)
        ).hexdigest()
        pf.write(f"leakmon {verdict}\n")
        pf.write(f"final {final}\n")
        pf.flush()
    if shipper is not None:
        shipper.close()
    engine.close()
    return 0


def oracle(schedule_seed: int, n_events: int, posmap_impl: str | None = None,
           tree_top_cache_levels: int | None = None,
           evict_every: int | None = None):
    """Uninterrupted in-process run: per-seq hashes + final state hash.

    Always serial (pipeline_depth=1) and single-chip (shards=1): the
    oracle is the pre-PR-10 resolve-before-next-dispatch program on one
    device, so a ``--pipeline-depth 2`` or ``--shards N`` chaos run
    proves the pipelined / mesh-sharded child recovers bit-identical to
    the SERIAL SINGLE-CHIP ground truth — composition equivalence and
    crash equivalence in one gate."""
    from grapevine_tpu.engine.batcher import GrapevineEngine
    from grapevine_tpu.engine.checkpoint import state_to_bytes

    engine = GrapevineEngine(
        _config(posmap_impl, tree_top_cache_levels, pipeline_depth=1,
                evict_every=evict_every),
        seed=ENGINE_SEED,
    )
    events = build_schedule(schedule_seed, n_events)
    hashes: dict[int, str] = {}
    for i, ev in enumerate(events):
        if ev[0] == "round":
            hashes[i + 1] = _resp_hash(engine.handle_queries(ev[2], ev[1]))
        else:
            engine.expire(ev[1], period=ev[2])
            hashes[i + 1] = "sweep"
    final = hashlib.sha256(
        state_to_bytes(engine.ecfg, engine.state)
    ).hexdigest()
    return hashes, final


def _parse_progress(path: str):
    seq_hashes: dict[int, str] = {}
    finals, leakmons = [], []
    try:
        with open(path) as fh:
            lines = fh.read().splitlines()
    except FileNotFoundError:
        return seq_hashes, finals, leakmons
    for line in lines:
        parts = line.split()
        if len(parts) != 2:
            continue  # torn progress line from a mid-write kill
        tag, val = parts
        if tag == "final":
            finals.append(val)
        elif tag == "leakmon":
            leakmons.append(val)
        elif tag.isdigit():
            seq_hashes[int(tag)] = val
    return seq_hashes, finals, leakmons


def _fork_cache(shared_dir: str) -> str:
    """Hardlink-clone the shared XLA compilation cache for ONE child
    launch. jax 0.4.x's persistent cache writes entries with a plain
    ``write_bytes`` — NOT atomic — so a SIGKILL mid-compile leaves a
    torn ``.cache`` prefix that every later process silently loads as
    a wrong executable (observed: bit-divergent replay the moment a
    kill site lands near a fresh compile, e.g. the delayed-eviction
    flush program compiling in the same event as the first
    checkpoint). Each launch therefore runs against a disposable fork
    of known-good entries; only launches that EXIT CLEANLY merge their
    new entries back (atomically) via :func:`_merge_cache`."""
    d = tempfile.mkdtemp(prefix="chaos-cache-fork-")
    for name in os.listdir(shared_dir):
        try:
            os.link(os.path.join(shared_dir, name), os.path.join(d, name))
        except OSError:  # pragma: no cover - cross-device fallback
            try:
                shutil.copyfile(os.path.join(shared_dir, name),
                                os.path.join(d, name))
            except OSError:
                pass
    return d


def _merge_cache(fork_dir: str, shared_dir: str) -> None:
    """Promote a CLEAN child's new cache entries into the shared dir
    with write-tmp + os.replace (the atomicity jax's own put lacks).
    Existing shared entries are never touched (jax entries are
    content-addressed by key)."""
    for name in os.listdir(fork_dir):
        dst = os.path.join(shared_dir, name)
        if os.path.exists(dst):
            continue
        tmp = dst + f".tmp.{os.getpid()}"
        try:
            shutil.copyfile(os.path.join(fork_dir, name), tmp)
            os.replace(tmp, dst)
        except OSError:  # pragma: no cover - best-effort cache
            try:
                os.unlink(tmp)
            except OSError:
                pass


def run_trial(trial: int, mode: str, rng: random.Random, args,
              oracle_hashes, oracle_final, cache_dir: str) -> list[str]:
    """One kill-recover-verify trial; returns a list of failure strings."""
    errors: list[str] = []
    if mode.startswith("flush.") and (args.evict_every or 1) <= 1:
        # the flush crash sites only exist under delayed eviction: at
        # E=1 the engine never reaches them and the "trial" would be a
        # clean run masquerading as kill coverage — say so instead
        print(
            f"trial {trial:3d} [{mode:>26s}]: SKIP "
            "(evict_every=1 — no flush sites; rerun with "
            "--evict-every > 1 for kill-at-flush coverage)",
            flush=True,
        )
        return errors
    with tempfile.TemporaryDirectory(prefix=f"chaos{trial}-") as state_dir:
        progress = os.path.join(state_dir, "progress.log")
        child_cmd = [
            sys.executable, os.path.abspath(__file__), "--child",
            "--state-dir", state_dir, "--progress", progress,
            "--events", str(args.events),
            "--schedule-seed", str(args.schedule_seed),
            "--checkpoint-every", str(args.checkpoint_every),
        ]
        if args.posmap_impl:
            child_cmd += ["--posmap-impl", args.posmap_impl]
        if args.tree_top_cache_levels is not None:
            child_cmd += ["--tree-top-cache-levels",
                          str(args.tree_top_cache_levels)]
        if args.pipeline_depth is not None:
            child_cmd += ["--pipeline-depth", str(args.pipeline_depth)]
        if args.evict_every is not None:
            child_cmd += ["--evict-every", str(args.evict_every)]
        if args.shards is not None:
            child_cmd += ["--shards", str(args.shards)]
        base_env = dict(
            os.environ,
            JAX_COMPILATION_CACHE_DIR=cache_dir,
            JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS", "cpu"),
        )
        base_env.pop("GRAPEVINE_FAULTS", None)
        if (args.shards or 1) > 1:
            # the child needs a mesh: force the virtual CPU device
            # count (before its jax init) unless the caller already set
            # one — the ORACLE stays single-chip in this process
            flags = base_env.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                base_env["XLA_FLAGS"] = (
                    f"{flags} --xla_force_host_platform_device_count="
                    f"{args.shards}"
                ).strip()
        kills = 0
        launch = 0
        while True:
            env = dict(base_env)
            # disposable cache fork per launch: a SIGKILL can tear the
            # non-atomic jax cache writes, and a torn entry silently
            # loads as a WRONG executable on the next launch (see
            # _fork_cache) — only clean exits merge entries back
            cache_fork = _fork_cache(cache_dir)
            env["JAX_COMPILATION_CACHE_DIR"] = cache_fork
            timer_kill = None
            if launch == 0:
                if mode == "timer":
                    timer_kill = rng.uniform(1.0, args.timer_max_s)
                else:
                    # checkpoint sites fire once per --checkpoint-every
                    # records, flush sites once per evict_every rounds,
                    # append sites once per record — scale the trigger
                    # count so the fault actually lands mid-run
                    if mode.startswith("checkpoint."):
                        cap = max(2, args.events // args.checkpoint_every)
                    elif mode.startswith("flush."):
                        cap = max(2, args.events // max(1, args.evict_every or 1))
                    else:
                        cap = max(2, args.events // 2)
                    env["GRAPEVINE_FAULTS"] = f"{mode}={rng.randrange(1, cap)}"
            proc = subprocess.Popen(
                child_cmd, env=env, cwd=REPO,
                stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
            )
            if timer_kill is not None:
                try:
                    proc.wait(timeout=timer_kill)
                except subprocess.TimeoutExpired:
                    proc.send_signal(signal.SIGKILL)
            _, err = proc.communicate()
            rc = proc.returncode
            if rc == 0:
                _merge_cache(cache_fork, cache_dir)
            shutil.rmtree(cache_fork, ignore_errors=True)
            if rc == 0:
                break
            if rc != -signal.SIGKILL:
                errors.append(
                    f"trial {trial} [{mode}]: child exited rc={rc} "
                    f"(want clean or SIGKILL): {err.decode()[-2000:]}"
                )
                return errors
            kills += 1
            launch += 1
            if launch > MAX_RESTARTS:
                errors.append(
                    f"trial {trial} [{mode}]: no clean run after "
                    f"{MAX_RESTARTS} restarts"
                )
                return errors
        seq_hashes, finals, leakmons = _parse_progress(progress)
        for seq, h in sorted(seq_hashes.items()):
            if oracle_hashes.get(seq) != h:
                errors.append(
                    f"trial {trial} [{mode}]: responses for round {seq} "
                    f"diverge from the uninterrupted run"
                )
        if not finals or finals[-1] != oracle_final:
            errors.append(
                f"trial {trial} [{mode}]: final recovered state is not "
                f"bit-identical to the uninterrupted run"
            )
        if not leakmons or leakmons[-1] != "PASS":
            errors.append(
                f"trial {trial} [{mode}]: leak monitor verdict "
                f"{leakmons[-1] if leakmons else 'missing'} (want PASS)"
            )
        if not errors:
            print(
                f"trial {trial:3d} [{mode:>26s}]: PASS "
                f"({kills} kill{'s' if kills != 1 else ''}, "
                f"{len(seq_hashes)}/{len(oracle_hashes)} rounds recorded)",
                flush=True,
            )
    return errors


def run_standby_trial(trial: int, mode: str, rng: random.Random, args,
                      oracle_hashes, oracle_final,
                      cache_dir: str) -> list[str]:
    """One kill-the-primary takeover trial (--standby).

    The parent process hosts a live :class:`StandbyReplica` (same
    geometry as the oracle: serial, single-chip — so its jitted
    programs are already warm from the oracle run, which is the hot
    part of "hot standby"). The child is the PRIMARY: it runs the
    schedule with ``--replicate-to`` pointed at the replica and is
    SIGKILLed ONCE at the armed fault site — including mid-flush and
    mid-fsync — with no restart. The parent then promotes the replica
    (fencing the dead primary's state dir, draining its durable tail
    off disk), drives the REMAINING schedule on the promoted engine,
    and holds the whole run to the uninterrupted serial oracle:
    per-round response hashes, final state bit-identity, leakmon PASS.
    RPO 0 for durable frames and RTO = the measured promote() wall
    time, printed per trial."""
    errors: list[str] = []
    if mode.startswith("flush.") and (args.evict_every or 1) <= 1:
        print(
            f"trial {trial:3d} [{mode:>26s}]: SKIP "
            "(evict_every=1 — no flush sites; rerun with "
            "--evict-every > 1 for kill-at-flush coverage)",
            flush=True,
        )
        return errors
    from grapevine_tpu.config import DurabilityConfig
    from grapevine_tpu.engine.checkpoint import state_to_bytes
    from grapevine_tpu.engine.journal import BatchJournal, JournalError
    from grapevine_tpu.engine.replication import StandbyReplica
    from grapevine_tpu.obs.leakmon import EngineLeakMonitor, LeakMonitorConfig

    events = build_schedule(args.schedule_seed, args.events)
    with tempfile.TemporaryDirectory(prefix=f"chaos{trial}-") as root:
        primary_dir = os.path.join(root, "primary")
        standby_dir = os.path.join(root, "standby")
        os.makedirs(primary_dir)
        os.makedirs(standby_dir)
        progress = os.path.join(root, "progress.log")
        # replication's standing requirement (engine/replication.py,
        # OPERATIONS.md §23): primary and standby share the root seal
        # key — a standby with its own key cannot unseal a single
        # shipped frame. Provision one key into both dirs up front,
        # exactly what a production secret mount does.
        key = bytes(rng.randrange(256) for _ in range(32))
        for d in (primary_dir, standby_dir):
            kp = os.path.join(d, "root.key")
            with open(kp, "wb") as fh:
                fh.write(key)
            os.chmod(kp, 0o600)
        replica = StandbyReplica(
            _config(args.posmap_impl, args.tree_top_cache_levels,
                    pipeline_depth=1, evict_every=args.evict_every,
                    shards=1),
            seed=ENGINE_SEED,
            durability=DurabilityConfig(
                state_dir=standby_dir,
                checkpoint_every_rounds=args.checkpoint_every,
                journal_fsync_every=1,
            ),
        )
        try:
            port = replica.listen()
            child_cmd = [
                sys.executable, os.path.abspath(__file__), "--child",
                "--state-dir", primary_dir, "--progress", progress,
                "--events", str(args.events),
                "--schedule-seed", str(args.schedule_seed),
                "--checkpoint-every", str(args.checkpoint_every),
                "--replicate-to", f"127.0.0.1:{port}",
            ]
            if args.posmap_impl:
                child_cmd += ["--posmap-impl", args.posmap_impl]
            if args.tree_top_cache_levels is not None:
                child_cmd += ["--tree-top-cache-levels",
                              str(args.tree_top_cache_levels)]
            if args.pipeline_depth is not None:
                child_cmd += ["--pipeline-depth", str(args.pipeline_depth)]
            if args.evict_every is not None:
                child_cmd += ["--evict-every", str(args.evict_every)]
            if args.shards is not None:
                child_cmd += ["--shards", str(args.shards)]
            env = dict(
                os.environ,
                JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS", "cpu"),
            )
            env.pop("GRAPEVINE_FAULTS", None)
            if (args.shards or 1) > 1:
                flags = env.get("XLA_FLAGS", "")
                if "xla_force_host_platform_device_count" not in flags:
                    env["XLA_FLAGS"] = (
                        f"{flags} --xla_force_host_platform_device_count="
                        f"{args.shards}"
                    ).strip()
            cache_fork = _fork_cache(cache_dir)
            env["JAX_COMPILATION_CACHE_DIR"] = cache_fork
            timer_kill = None
            if mode == "timer":
                timer_kill = rng.uniform(1.0, args.timer_max_s)
            else:
                if mode.startswith("checkpoint."):
                    cap = max(2, args.events // args.checkpoint_every)
                elif mode.startswith("flush."):
                    cap = max(2, args.events // max(1, args.evict_every or 1))
                else:
                    cap = max(2, args.events // 2)
                env["GRAPEVINE_FAULTS"] = f"{mode}={rng.randrange(1, cap)}"
            proc = subprocess.Popen(
                child_cmd, env=env, cwd=REPO,
                stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
            )
            if timer_kill is not None:
                try:
                    proc.wait(timeout=timer_kill)
                except subprocess.TimeoutExpired:
                    proc.send_signal(signal.SIGKILL)
            _, err = proc.communicate()
            rc = proc.returncode
            if rc == 0:
                _merge_cache(cache_fork, cache_dir)
            shutil.rmtree(cache_fork, ignore_errors=True)
            if rc not in (0, -signal.SIGKILL):
                errors.append(
                    f"trial {trial} [standby:{mode}]: primary exited "
                    f"rc={rc} (want clean or SIGKILL): "
                    f"{err.decode()[-2000:]}"
                )
                return errors
            killed = rc == -signal.SIGKILL
            # fenced takeover: plant the epoch fence in the dead
            # primary's dir, drain its durable tail, complete any
            # pending flush — the measured RTO
            info = replica.promote(primary_state_dir=primary_dir)
            eng = replica.engine
            monitor = EngineLeakMonitor.for_engine(
                eng, LeakMonitorConfig(window_rounds=64)
            )
            eng.attach_leakmon(monitor)
            start = _events_done(events, eng.durability.seq,
                                 eng.evict_every)
            with open(progress, "a") as pf:
                _run_events(eng, events, start, pf)
                monitor.close()
                verdict = monitor.verdict()["verdict"]
                final = hashlib.sha256(
                    state_to_bytes(eng.ecfg, eng.state)
                ).hexdigest()
                pf.write(f"leakmon {verdict}\n")
                pf.write(f"final {final}\n")
                pf.flush()
            # split-brain guard, live: a revived incarnation of the
            # killed primary must be refused at journal-open time
            try:
                stale = BatchJournal(primary_dir, replica.dm.root_key,
                                     replica.dm.ecfg)
                for _rec in stale.replay():
                    pass
                stale.open_for_append()
            except JournalError:
                pass
            else:
                errors.append(
                    f"trial {trial} [standby:{mode}]: revived stale "
                    "primary was NOT refused by the epoch fence"
                )
        finally:
            replica.close()
        seq_hashes, finals, leakmons = _parse_progress(progress)
        for seq, h in sorted(seq_hashes.items()):
            if oracle_hashes.get(seq) != h:
                errors.append(
                    f"trial {trial} [standby:{mode}]: responses for "
                    f"round {seq} diverge from the uninterrupted run"
                )
        if not finals or finals[-1] != oracle_final:
            errors.append(
                f"trial {trial} [standby:{mode}]: promoted final state "
                "is not bit-identical to the uninterrupted run"
            )
        if not leakmons or leakmons[-1] != "PASS":
            errors.append(
                f"trial {trial} [standby:{mode}]: leak monitor verdict "
                f"{leakmons[-1] if leakmons else 'missing'} (want PASS)"
            )
        if not errors:
            print(
                f"trial {trial:3d} [{mode:>26s}]: PASS "
                f"({'killed' if killed else 'clean'}, promoted epoch "
                f"{info['epoch']}, drained {info['drained_frames']} "
                f"durable frames, rto {info['rto_seconds'] * 1e3:.0f}ms, "
                f"{len(seq_hashes)}/{len(oracle_hashes)} rounds recorded)",
                flush=True,
            )
    return errors


def run_trials(n_trials: int, args=None, modes=None) -> list[str]:
    """Run ``n_trials`` randomized trials (or one per entry of
    ``modes``); returns accumulated failures. Importable by the slow
    chaos test (tests/test_chaos_recovery.py)."""
    from grapevine_tpu.testing.faults import ALL_POINTS

    args = args or parse_args([])
    rng = random.Random(args.seed)
    cache_dir = os.path.join(
        tempfile.gettempdir(), "grapevine_chaos_jax_cache"
    )
    os.makedirs(cache_dir, exist_ok=True)
    t0 = time.monotonic()
    oracle_hashes, oracle_final = oracle(
        args.schedule_seed, args.events, args.posmap_impl,
        args.tree_top_cache_levels, args.evict_every,
    )
    print(f"oracle: {len(oracle_hashes)} events in "
          f"{time.monotonic() - t0:.1f}s", flush=True)
    if modes is None:
        modes = [
            rng.choice(list(ALL_POINTS) + ["timer"]) for _ in range(n_trials)
        ]
    failures: list[str] = []
    trial_fn = run_standby_trial if args.standby else run_trial
    for trial, mode in enumerate(modes):
        failures.extend(
            trial_fn(trial, mode, rng, args, oracle_hashes, oracle_final,
                     cache_dir)
        )
    return failures


def parse_args(argv):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--child", action="store_true")
    p.add_argument("--state-dir")
    p.add_argument("--progress")
    p.add_argument("--trials", type=int, default=50)
    p.add_argument("--points", action="store_true",
                   help="one trial per fault-injection site instead of "
                   "randomized trials")
    p.add_argument("--events", type=int, default=24)
    p.add_argument("--schedule-seed", type=int, default=11)
    p.add_argument("--checkpoint-every", type=int, default=5)
    p.add_argument("--seed", type=int, default=1234)
    p.add_argument("--timer-max-s", type=float, default=12.0)
    p.add_argument("--standby", action="store_true",
                   help="hot-standby takeover trials instead of "
                   "restart-in-place: the child primary ships its "
                   "journal to an in-parent StandbyReplica "
                   "(engine/replication.py) and is SIGKILLed ONCE at "
                   "the armed site with no restart; the parent "
                   "promotes (fenced), drives the remaining schedule "
                   "on the promoted engine, and holds the whole run "
                   "to the serial oracle bit-for-bit with leakmon "
                   "PASS. Prints the measured RTO per trial")
    p.add_argument("--replicate-to", default=None,
                   help="(child) ship the journal to this host:port "
                   "while running — set by --standby trials")
    p.add_argument("--posmap-impl", default=None,
                   choices=["flat", "recursive"],
                   help="position-map implementation under test "
                   "(oram/posmap.py); default = the engine auto (flat)")
    p.add_argument("--tree-top-cache-levels", type=int, default=None,
                   help="tree-top cache depth under test "
                   "(oram/path_oram.py); default = the engine auto")
    p.add_argument("--evict-every", type=int, default=None,
                   help="delayed-eviction cadence E under test (engine/"
                   "batcher.py; oram/round.py:oram_flush): fetch rounds "
                   "accumulate in the private buffer and the flush "
                   "journals (KIND_FLUSH) + dispatches with the E-th "
                   "round — the flush.pre/post_dispatch crash sites are "
                   "the kill-at-flush windows. The oracle runs the SAME "
                   "E (serial), so trials prove crash recovery, not "
                   "cross-E equivalence (that is tests/test_evict.py's "
                   "logical-content contract). Default = engine auto (1)")
    p.add_argument("--shards", type=int, default=None,
                   help="bucket-axis shard count under test (parallel/"
                   "mesh.py via engine/batcher.py): the child runs the "
                   "sharded step/flush programs on a virtual CPU mesh "
                   "(the parent exports the device-count XLA flag), "
                   "while the ORACLE stays single-chip — so every "
                   "trial proves crash recovery AND sharded<->single-"
                   "chip bit-equivalence in one gate (the pipeline-"
                   "depth discipline). Combine with --evict-every > 1 "
                   "to land the flush.pre/post_dispatch kills on the "
                   "owner-masked sharded flush. Default = engine auto "
                   "(1)")
    p.add_argument("--pipeline-depth", type=int, default=None,
                   choices=[1, 2],
                   help="round-pipeline depth under test (engine/"
                   "batcher.py): 2 keeps a round mid-flight on the "
                   "device while the next one journals + fsyncs — the "
                   "crash windows PR 10 opened; the oracle always runs "
                   "serial (depth 1), so the trial also proves depth "
                   "bit-equivalence. Default = the engine auto")
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv if argv is not None else sys.argv[1:])
    if args.child:
        return run_child(args)
    from grapevine_tpu.testing.faults import ALL_POINTS

    modes = list(ALL_POINTS) + ["timer"] if args.points else None
    failures = run_trials(args.trials, args, modes=modes)
    for f in failures:
        print(f"CHAOS FAILURE: {f}", file=sys.stderr)
    n = len(modes) if modes else args.trials
    print(f"chaos: {n} trials, {len(failures)} failure(s)")
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
