"""Engine round-cost scaling study: executed evidence for the SHAPE of
PERF.md's cost model (per-round work ~ B·path_len rows of gather/
scatter + cipher + eviction sort), on whatever backend is available.

The absolute numbers on a CPU backend say nothing about TPU throughput;
the SCALING — how round time moves with batch size B and capacity N —
transfers, because it is a property of the program's operation counts,
not the backend's speed. The model predicts:

- round time ≈ fixed + c·B·log2(N): linear in B at fixed N, and the
  per-op cost B·plen/B = plen grows only logarithmically with N;
- ops/s therefore RISES with B until HBM/FLOP saturation (amortizing
  the fixed round overhead) — the whole premise of batched rounds.

Run:  python tools/scaling_study.py [--out SCALING.md]
Writes a markdown table + least-squares fit. Uses scan-fused rounds
(bench.py's throughput methodology) with the cipher ON.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def measure(cap_log2: int, batch: int, n_rounds: int = 8):
    import jax

    import bench

    cfg, ecfg, state, step = bench._mk_engine(
        1 << cap_log2, 1 << max(8, cap_log2 - 8), batch,
        cipher_impl="jnp",
    )
    batches = bench.make_batches(4, batch)
    t0 = time.perf_counter()
    state, resp, _ = step(ecfg, state, batches[0])
    jax.block_until_ready(resp)
    compile_s = time.perf_counter() - t0
    _, _times, total = bench._run_rounds(ecfg, state, step, batches[1:], n_rounds)
    per_round_ms = total / n_rounds * 1e3
    return per_round_ms, compile_s


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(_REPO, "SCALING.md"))
    args = ap.parse_args()

    import jax

    # honor an explicit JAX_PLATFORMS against platform-pinning site
    # hooks; otherwise measure whatever backend jax selects (that is
    # the point of the tool — CPU in CI, the real chip on a TPU host)
    want = os.environ.get("JAX_PLATFORMS")
    if want:
        jax.config.update("jax_platforms", want)
    backend = jax.default_backend()

    grid_b = [(16, b) for b in (64, 256, 1024)]          # B sweep at 2^16
    grid_n = [(n, 256) for n in (14, 16, 18, 20)]        # N sweep at B=256
    rows = []
    for cl, b in grid_b + grid_n:
        ms, comp = measure(cl, b)
        rows.append((cl, b, ms, comp))
        print(f"cap=2^{cl} B={b}: {ms:.1f} ms/round "
              f"({b / ms * 1e3:.0f} ops/s, compile {comp:.0f}s)", flush=True)

    # fits: round_ms vs B at fixed N (linear), per-op ms vs log2(N) at
    # fixed B (linear in path length)
    import numpy as np

    bs = np.array([r[1] for r in rows[:len(grid_b)]], float)
    ms_b = np.array([r[2] for r in rows[:len(grid_b)]], float)
    slope_b, icept_b = np.polyfit(bs, ms_b, 1)
    ns = np.array([r[0] for r in rows[len(grid_b):]], float)
    ms_n = np.array([r[2] for r in rows[len(grid_b):]], float)
    slope_n, icept_n = np.polyfit(ns, ms_n, 1)

    lines = [
        "# Engine round-cost scaling (executed)",
        "",
        f"Backend: `{backend}` — absolute times are backend-bound; the",
        "SCALING is the evidence (tools/scaling_study.py docstring).",
        "",
        "| capacity | batch B | ms/round | engine ops/s | compile s |",
        "|---|---|---|---|---|",
    ]
    for cl, b, ms, comp in rows:
        lines.append(
            f"| 2^{cl} | {b} | {ms:.1f} | {b / ms * 1e3:.0f} | {comp:.0f} |")
    per_op = [(b, m / b) for _, b, m, _ in rows[:len(grid_b)]]
    lines += [
        "",
        f"- N sweep at B=256: round_ms ≈ {icept_n:.1f} + {slope_n:.2f}·log2(N) —",
        "  per-round cost grows ~linearly in path length (log N), matching",
        "  the B·plen gather/scatter + cipher term of PERF.md's model",
        "  (the repeated 2^16/B=256 row re-measures the first grid point:",
        "  its ~instant compile is the in-process executable cache hitting",
        "  on identical shapes);",
        f"- B sweep at 2^16: round_ms ≈ {icept_b:.1f} + {slope_b:.4f}·B",
        "  (least-squares; see the per-op view below for why B-linear is",
        "  only part of the story on a scalar backend);",
        "- B sweep at 2^16, per-op ms: "
        + ", ".join(f"{m:.2f} @B={b}" for b, m in per_op) + ".",
        "  On a SCALAR backend the per-op cost stops improving with B",
        "  because the [B,B] slot-order semantics (one-hot matmuls and",
        "  masks, O(B²) work) come to dominate — which is exactly the",
        "  term the design places on the MXU, where a [2048,2048] bf16",
        "  matmul is microseconds. The B-amortization of fixed dispatch",
        "  cost is measured separately (PERF.md: scan-fused vs blocking",
        "  rounds); this sweep instead bounds the non-MXU share of the",
        "  round, the part a TPU actually pays per op.",
        "",
    ]
    with open(args.out, "w") as f:
        f.write("\n".join(lines))
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
