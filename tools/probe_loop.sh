#!/bin/bash
# TPU relay probe loop (VERDICT r4 next-round #1: "retry periodically
# all round"). Appends one line per attempt to PROBELOG_r5.md; on the
# first success it writes /tmp/TPU_UP and exits so the session can run
# the heavy TPU work serialized (the relay is one weak core).
LOG=/root/repo/PROBELOG_r5.md
if [ ! -f "$LOG" ]; then
  {
    echo "# TPU relay probe log — round 5"
    echo
    echo "One line per attempt. Probe = 256x256 matmul on the default"
    echo "backend in a fresh subprocess, 300 s timeout (bench.py's probe)."
    echo
  } >> "$LOG"
fi
while true; do
  ts=$(date -u +%Y-%m-%dT%H:%M:%SZ)
  out=$(timeout 300 python - <<'EOF' 2>&1
import time, jax, jax.numpy as jnp
t0 = time.time()
x = jnp.ones((256, 256), jnp.float32)
(x @ x).block_until_ready()
print(f"PROBE_OK {jax.default_backend()} {len(jax.devices())}dev {time.time()-t0:.1f}s")
EOF
)
  rc=$?
  line=$(echo "$out" | grep PROBE_OK | head -1)
  if [ -n "$line" ]; then
    echo "- $ts: **UP** — $line" >> "$LOG"
    echo "$ts $line" > /tmp/TPU_UP
    exit 0
  else
    err=$(echo "$out" | tail -1 | cut -c1-120)
    [ $rc -eq 124 ] && err="timeout after 300s"
    echo "- $ts: down (rc=$rc; $err)" >> "$LOG"
  fi
  sleep 420
done
