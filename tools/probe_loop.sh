#!/bin/bash
# TPU relay probe loop (VERDICT r4 next-round #1: "retry periodically
# all round"). Appends one line per attempt to PROBELOG_r5.md; on each
# success it harvests TPU evidence via tools/tpu_capture.py (quick pass
# first, then full-size), then RESUMES probing — window 1 closed after
# ~5 minutes with most stages uncaptured, so later windows must
# re-harvest whatever is still missing (the artifact is append-only;
# per-window skip logic below keeps re-runs cheap).
#
# "UP" requires a TPU-class backend name: "tpu" (direct plugin) or
# "axon" (the relay tunnel's platform name, BENCH_r02.json). A cpu
# fallback probe must NOT stop the loop or trigger a harvest.
#
# Cadence: window 1 lasted ~5 min, so the down-cycle must be shorter
# than that: 120 s probe timeout (a live relay answers in ~10 s) +
# 150 s sleep ≈ 4.5 min worst-case detection latency.
LOG=/root/repo/PROBELOG_r5.md
OUT=/root/repo/TPURUN_r5.jsonl
# Hard deadline (epoch s): the axon tunnel is single-claim, so a
# capture still running when the DRIVER's end-of-round bench starts
# would force BENCH_r05 into cpu-fallback — the loop must be long gone
# by then. Override via PROBE_DEADLINE for other sessions.
DEADLINE=${PROBE_DEADLINE:-1785507900}
if [ ! -f "$LOG" ]; then
  {
    echo "# TPU relay probe log — round 5"
    echo
    echo "One line per attempt. Probe = 256x256 matmul on the default"
    echo "backend in a fresh subprocess, 300 s timeout (bench.py's probe)."
    echo
  } >> "$LOG"
fi
while true; do
  now=$(date -u +%s)
  left=$((DEADLINE - now))
  if [ "$left" -le 180 ]; then
    echo "- $(date -u +%Y-%m-%dT%H:%M:%SZ): probe loop exiting (deadline; tunnel released for the driver bench)" >> "$LOG"
    exit 0
  fi
  ts=$(date -u +%Y-%m-%dT%H:%M:%SZ)
  out=$(timeout 120 python - <<'EOF' 2>&1
import time, jax, jax.numpy as jnp
t0 = time.time()
x = jnp.ones((256, 256), jnp.float32)
(x @ x).block_until_ready()
print(f"PROBE_OK {jax.default_backend()} {len(jax.devices())}dev {time.time()-t0:.1f}s")
EOF
)
  rc=$?
  line=$(echo "$out" | grep -E 'PROBE_OK (tpu|axon)' | head -1)
  if [ -n "$line" ]; then
    echo "- $ts: **UP** — $line" >> "$LOG"
    echo "$ts $line" > /tmp/TPU_UP
    # Harvest immediately — the window may be brief. Quick pass first
    # (guarantees SOME TPU numbers), then a full-size pass that skips
    # only the size-independent stages the quick pass actually captured
    # (checked in the artifact, not assumed).
    cd /root/repo
    # scope the skip decision to THIS window's lines: the artifact is
    # append-only across windows, and a passing stage from an earlier
    # window (possibly older code) must not suppress a re-run
    n0=$(wc -l < "$OUT" 2>/dev/null || echo 0)
    # never let a capture run past the deadline (minus teardown margin)
    cap=$((DEADLINE - $(date -u +%s) - 240))
    [ "$cap" -gt 7200 ] && cap=7200
    if [ "$cap" -lt 600 ]; then
      echo "- $(date -u +%Y-%m-%dT%H:%M:%SZ): window found but too close to deadline; not capturing" >> "$LOG"
      exit 0
    fi
    # the quick pass exists to guarantee SOME numbers from a short
    # window; once any window has banked a quick headline, later
    # windows skip straight to the full-size pass (window 1 lasted
    # ~5 min — a re-run of the quick pass would have eaten all of it)
    if grep -q '"stage": "headline".*"ops_per_sec"' "$OUT" 2>/dev/null; then
      echo "- $(date -u +%Y-%m-%dT%H:%M:%SZ): quick pass skipped (headline already banked)" >> "$LOG"
    else
      timeout "$cap" python tools/tpu_capture.py --quick \
        >> /tmp/tpu_capture_quick.log 2>&1
      echo "- $(date -u +%Y-%m-%dT%H:%M:%SZ): quick capture rc=$? (TPURUN_r5.jsonl)" >> "$LOG"
    fi
    fresh=$(tail -n +$((n0 + 1)) "$OUT" 2>/dev/null)
    skip=""
    echo "$fresh" | grep -q '"stage": "mosaic".*"bit_identical": true' \
      && skip="mosaic"
    # success = measurement line present AND no error line: the stage
    # emits its measurements BEFORE raising on a failed invariant, and
    # the raise adds a separate {"stage": "oblivious", ... "error"} line
    if echo "$fresh" | grep -q '"stage": "oblivious".*"transcripts_equal"' \
      && ! echo "$fresh" | grep -q '"stage": "oblivious".*"error"'; then
      skip="${skip:+$skip,}oblivious"
    fi
    cap=$((DEADLINE - $(date -u +%s) - 240))
    [ "$cap" -gt 7200 ] && cap=7200
    if [ "$cap" -lt 600 ]; then
      echo "- $(date -u +%Y-%m-%dT%H:%M:%SZ): full pass skipped (deadline)" >> "$LOG"
      exit 0
    fi
    timeout "$cap" python tools/tpu_capture.py ${skip:+--skip "$skip"} \
      >> /tmp/tpu_capture_full.log 2>&1
    echo "- $(date -u +%Y-%m-%dT%H:%M:%SZ): full capture rc=$? (skip='${skip}', TPURUN_r5.jsonl)" >> "$LOG"
    # resume probing: the next window re-harvests anything still missing
    sleep 150
  else
    err=$(echo "$out" | tail -1 | cut -c1-120)
    [ $rc -eq 124 ] && err="timeout after 120s"
    echo "- $ts: down (rc=$rc; $err)" >> "$LOG"
    sleep 150
  fi
done
