#!/usr/bin/env python
"""Checkpoint/journal seal gate (CI; invoked by a tier-1 test).

Drives a fixture engine with durability on — every request carrying a
loud plaintext marker in its payload, recipient, and auth identity —
then scans every file the durability subsystem wrote and asserts none
of them contains:

- the payload marker bytes (message content must be sealed);
- any fixture recipient/auth identity bytes (metadata must be sealed);
- the 32-byte root seal key (key material must never leak into data
  files; the key lives only in its own 0600 key file, which the scan
  skips — it IS the key).

This is the durability analog of tools/check_telemetry_policy.py: the
property OPERATIONS.md §11 promises ("sealed files are ciphertext —
a stolen state volume without the key reveals sizes and cadence only"),
enforced against the real write path rather than trusted by review.

Run directly::

    JAX_PLATFORMS=cpu python tools/check_checkpoint_seal.py
"""

from __future__ import annotations

import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: recognizable, high-redundancy plaintext: a sealing slip of even a
#: few bytes of keystream reuse would still contain a full marker copy
PAYLOAD_MARKER = b"GRAPEVINE-SEAL-CHECK-PLAINTEXT-MARKER/"


def _ident(n: int) -> bytes:
    base = b"SEALCHECK-IDENT-%02d/" % n
    return (base + b"\xaa" * 32)[:32]


def run_fixture(state_dir: str) -> dict:
    """Rounds + a sweep + checkpoints against ``state_dir``; returns the
    byte patterns that must NOT appear in any sealed file."""
    from grapevine_tpu.config import DurabilityConfig, GrapevineConfig
    from grapevine_tpu.engine.batcher import GrapevineEngine
    from grapevine_tpu.wire import constants as C
    from grapevine_tpu.wire.records import QueryRequest, RequestRecord

    cfg = GrapevineConfig(
        max_messages=64, max_recipients=8, mailbox_cap=4,
        batch_size=4, stash_size=64, bucket_cipher_rounds=0,
    )
    dcfg = DurabilityConfig(state_dir=state_dir, checkpoint_every_rounds=3)
    engine = GrapevineEngine(cfg, seed=9, durability=dcfg)
    reps = C.PAYLOAD_SIZE // len(PAYLOAD_MARKER) + 1
    payload = (PAYLOAD_MARKER * reps)[: C.PAYLOAD_SIZE]
    now = 1_700_000_000
    for i in range(6):
        reqs = [
            QueryRequest(
                request_type=C.REQUEST_TYPE_CREATE,
                auth_identity=_ident(i % 4),
                auth_signature=b"\x01" * C.SIGNATURE_SIZE,
                record=RequestRecord(
                    msg_id=C.ZERO_MSG_ID,
                    recipient=_ident((i + 1) % 4),
                    payload=payload,
                ),
            )
            for _ in range(3)
        ]
        engine.handle_queries(reqs, now + i)
    engine.expire(now + 10, period=10_000)
    engine.checkpoint_now()
    root_key = engine.durability.root_key
    engine.close()
    return {
        "payload marker": PAYLOAD_MARKER,
        "recipient/auth identity": _ident(0)[:16],
        "root seal key": root_key,
    }


def scan(state_dir: str, patterns: dict) -> list[str]:
    violations = []
    for name in sorted(os.listdir(state_dir)):
        if name == "root.key":
            continue  # the key file is the key; everything else is data
        path = os.path.join(state_dir, name)
        if not os.path.isfile(path):
            continue
        with open(path, "rb") as fh:
            blob = fh.read()
        for label, pattern in patterns.items():
            if pattern in blob:
                violations.append(
                    f"{name}: contains plaintext {label} "
                    f"({len(pattern)} marker bytes found in a sealed file)"
                )
    return violations


def main() -> int:
    with tempfile.TemporaryDirectory(prefix="sealcheck-") as state_dir:
        patterns = run_fixture(state_dir)
        files = sorted(
            n for n in os.listdir(state_dir)
            if os.path.isfile(os.path.join(state_dir, n))
        )
        if not any(n.startswith("ckpt-") for n in files) or not any(
            n.startswith("journal-") for n in files
        ):
            print(
                f"SEAL GATE BROKEN: fixture wrote no checkpoint/journal "
                f"files to scan (saw {files})", file=sys.stderr,
            )
            return 1
        violations = scan(state_dir, patterns)
    for v in violations:
        print(f"CHECKPOINT SEAL VIOLATION: {v}", file=sys.stderr)
    if not violations:
        print(
            f"checkpoint seal: clean — {len(files)} state file(s) hold "
            "no plaintext payload, identity, or key material"
        )
    return 1 if violations else 0


if __name__ == "__main__":
    raise SystemExit(main())
