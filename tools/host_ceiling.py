"""Host serving-path ceiling: loopback gRPC with the engine stubbed out.

Measures the throughput of everything the host does per op — client-side
sign + AEAD seal, gRPC loopback, server envelope decode, session lookup,
AEAD open, challenge lockstep, request unpack/validate, batched sr25519
verification, scheduling, response seal — with the device round replaced
by an instant canned response. This is the frontend's ceiling: a device
engine faster than this number is wasted (VERDICT r4 weak #3).

Run:  python tools/host_ceiling.py [--clients 32] [--ops 40] [--batch 64]
                                   [--legacy]
``--legacy`` disables the native STROBE ops and the OpenSSL ChaCha
backend to reproduce the pre-lever host path for before/after deltas.

Client and server share one interpreter (and the GIL), so the number is
a lower bound on a real deployment where clients are remote; the per-
component attribution lives in PERF.md's host table.
"""

from __future__ import annotations

import argparse
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


class _CannedPending:
    """Stands in for engine.PendingRound: resolves instantly."""

    def __init__(self, resps):
        self._resps = resps

    def resolve(self):
        return self._resps


def _stub_engine(engine):
    """Replace the device round with a canned constant-time response.
    Returns a mutable [rounds, ops] counter the stub updates."""
    from grapevine_tpu.wire import constants as C
    from grapevine_tpu.wire.records import QueryResponse, Record

    counter = [0, 0]

    def handle_queries_async(reqs, now):
        counter[0] += 1
        counter[1] += len(reqs)
        resp = QueryResponse(
            status_code=C.STATUS_CODE_SUCCESS,
            record=Record(
                msg_id=b"\x01" * 16,
                sender=b"\x02" * 32,
                recipient=b"\x03" * 32,
                timestamp=int(now),
                payload=b"\x00" * C.PAYLOAD_SIZE,
            ),
        )
        return _CannedPending([resp] * len(reqs))

    engine.handle_queries_async = handle_queries_async
    return counter


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--clients", type=int, default=32)
    ap.add_argument("--ops", type=int, default=40, help="ops per client")
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--legacy", action="store_true",
                    help="pre-lever host path (pure-Python STROBE + ChaCha)")
    args = ap.parse_args()

    if args.legacy:
        # Disable exactly the round-5 host levers (native STROBE ops,
        # one-crossing challenge, OpenSSL ChaCha) while KEEPING the
        # native MSM and the native Keccak permutation (both shipped in
        # r4) — so the delta isolates this round's levers, not all of C.
        from grapevine_tpu.session import chacha, merlin, schnorrkel

        chacha._Cipher = None
        merlin._native_strobe = lambda: None
        schnorrkel._challenge_scalar = schnorrkel._challenge_scalar_pure

    import jax

    jax.config.update("jax_platforms", "cpu")

    from grapevine_tpu.config import GrapevineConfig
    from grapevine_tpu.server.client import GrapevineClient
    from grapevine_tpu.server.service import GrapevineServer
    from grapevine_tpu.wire import constants as C

    cfg = GrapevineConfig(
        max_messages=1 << 10, max_recipients=1 << 8, batch_size=args.batch,
        bucket_cipher_rounds=0,
    )
    server = GrapevineServer(config=cfg)
    counter = _stub_engine(server.engine)
    port = server.start("insecure-grapevine://127.0.0.1:0")
    try:
        clients = [
            GrapevineClient(f"insecure-grapevine://127.0.0.1:{port}",
                            identity_seed=(i + 1).to_bytes(4, "little") * 8)
            for i in range(args.clients)
        ]
        for c in clients:
            c.auth()

        lat: list[float] = []
        errs: list[Exception] = []
        lock = threading.Lock()
        start = threading.Barrier(args.clients + 1)

        def run(c):
            mine = []
            try:
                start.wait()
                for i in range(args.ops):
                    t0 = time.perf_counter()
                    r = c.create(recipient=b"\x03" * 32,
                                 payload=bytes([i & 0xFF]) * C.PAYLOAD_SIZE)
                    assert r.status_code == C.STATUS_CODE_SUCCESS
                    mine.append(time.perf_counter() - t0)
            except Exception as e:  # pragma: no cover
                errs.append(e)
            with lock:
                lat.extend(mine)

        threads = [threading.Thread(target=run, args=(c,)) for c in clients]
        for t in threads:
            t.start()
        start.wait()
        t0 = time.perf_counter()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        if errs:
            raise errs[0]
        n = args.clients * args.ops
        rounds = counter[0]
        lat.sort()
        print({
            "mode": "legacy" if args.legacy else "current",
            "ops": n,
            "ops_per_sec": round(n / wall, 1),
            "p50_ms": round(lat[len(lat) // 2] * 1e3, 2),
            "p99_ms": round(lat[int(len(lat) * 0.99) - 1] * 1e3, 2),
            "rounds": rounds,
            "avg_round_fill": round(n / rounds, 1) if rounds else None,
            "batch": args.batch,
            "clients": args.clients,
        })
    finally:
        server.stop()


if __name__ == "__main__":
    main()
