#!/usr/bin/env python
"""CI gate: the position-map lookup's access schedule is index-blind.

The recursive position map's obliviousness claim (oram/posmap.py) is
that resolving a batch of B positions performs a FIXED schedule of
device memory accesses — the same number of gathers and scatters, in
the same program, no matter which indices are queried (duplicates,
all-same, all-dummy, anything). The jaxpr-audit pattern of PR 3/PR 5
(no-[B,B] / zero-sort-HLO gates) extends here to the access census:

1. trace ``lookup_remap_round`` with the *indices baked in as concrete
   constants* for several adversarially different index sets (all
   distinct, all identical, all dummy, mixed duplicates). Constants are
   the strongest form of the check: a data-dependent implementation —
   a Python-level branch on duplicates, a shortcut for dummy batches, a
   per-unique-index loop — would trace to *different* programs, which
   tracer-level (shape-only) audits can never see;
2. assert the full primitive census (every equation, recursively into
   sub-jaxprs) is IDENTICAL across all index sets, and in particular
   the gather/scatter counts are a fixed positive constant of the
   geometry;
3. assert no data-dependent control flow anywhere in the traced lookup
   (``cond``/``while``: a predicate on secret indices could skip
   accesses at run time even under a fixed trace);
4. positive control: the flat impl's census differs from the recursive
   one's (one gather + one scatter vs the internal ORAM round), proving
   the census actually distinguishes access schedules rather than
   vacuously passing.

Wired into tier-1 next to check_telemetry_policy / check_perf_regression
via tests/test_posmap.py; standalone: ``python tools/check_posmap_oblivious.py``.

Since ISSUE 12 this is a thin wrapper over the shared analyzer core
(grapevine_tpu/analysis/jaxpr_walk.py) — the census here, the tree-cache
tool's, and the taint analyzer's all walk the identical equation stream,
so the three gates cannot drift. CLI and exit codes are unchanged.
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from grapevine_tpu.analysis.jaxpr_walk import (  # noqa: E402
    ACCESS_PRIMS as _ACCESS_PRIMS,
    CONTROL_PRIMS as _CONTROL_PRIMS,
    census as _census,
)


def _index_sets(cfg, b: int):
    """Adversarially different query batches (concrete u32[b])."""
    import numpy as np

    dummy = cfg.dummy_index
    distinct = np.arange(b, dtype=np.uint32) % np.uint32(cfg.blocks)
    same = np.zeros(b, np.uint32)
    all_dummy = np.full(b, dummy, np.uint32)
    rng = np.random.default_rng(7)
    mixed = rng.integers(0, cfg.blocks + 1, b).astype(np.uint32)
    return {
        "distinct": distinct,
        "all_same": same,
        "all_dummy": all_dummy,
        "mixed_dups": mixed,
    }


def _trace_lookup(cfg, idxs, b: int, occ_impl: str, sort_impl: str):
    """Jaxpr of one whole-batch lookup+remap with ``idxs`` constant."""
    import jax
    import jax.numpy as jnp

    from grapevine_tpu.oram.path_oram import init_oram
    from grapevine_tpu.oram.posmap import lookup_remap_round
    from grapevine_tpu.oram.round import occurrence_masks, occurrence_masks_sorted

    state = jax.eval_shape(lambda: init_oram(cfg, jax.random.PRNGKey(0)))
    pm_shape = state.posmap
    il = cfg.posmap.inner_leaves if cfg.posmap is not None else 1
    cidxs = jnp.asarray(idxs)

    def run(pm, nl, dl, pm_nl, pm_dl):
        if occ_impl == "scan":
            fo, lo, _ = occurrence_masks_sorted(
                cidxs, cfg.dummy_index, sort_impl=sort_impl,
                key_bits=max(1, cfg.dummy_index.bit_length()),
            )
        else:
            fo, lo, _ = occurrence_masks(cidxs, cfg.dummy_index)
        return lookup_remap_round(
            cfg, pm, cidxs, nl, dl, fo, lo,
            pm_new_leaves=pm_nl if cfg.posmap is not None else None,
            pm_dummy_leaves=pm_dl if cfg.posmap is not None else None,
            occ_impl=occ_impl, sort_impl=sort_impl,
        )

    u32 = jnp.uint32
    lf = jax.ShapeDtypeStruct((b,), u32)
    return jax.make_jaxpr(run)(
        pm_shape, lf, lf,
        jax.ShapeDtypeStruct((b,), u32) if il else lf, lf,
    )


def check_posmap_access_schedule(
    b: int = 16, occ_impl: str = "dense", sort_impl: str = "xla",
    verbose: bool = False,
) -> dict:
    """Run the audit; returns the census summary, raises AssertionError
    on any violation."""
    from grapevine_tpu.oram.path_oram import OramConfig
    from grapevine_tpu.oram.posmap import derive_posmap_spec

    flat_cfg = OramConfig(height=4, value_words=4, n_blocks=32)
    rec_cfg = OramConfig(
        height=4, value_words=4, n_blocks=32,
        posmap=derive_posmap_spec(32),
    )

    out = {}
    for name, cfg in (("flat", flat_cfg), ("recursive", rec_cfg)):
        censuses = {}
        for iname, idxs in _index_sets(cfg, b).items():
            c = _census(_trace_lookup(cfg, idxs, b, occ_impl, sort_impl))
            censuses[iname] = c
        base_name, base = next(iter(censuses.items()))
        for iname, c in censuses.items():
            assert c == base, (
                f"{name} posmap lookup traces a DIFFERENT program for "
                f"index set {iname!r} vs {base_name!r}: "
                f"{(c - base) + (base - c)} — the access schedule "
                "depends on the queried indices"
            )
        n_access = sum(base[p] for p in _ACCESS_PRIMS)
        n_control = sum(base[p] for p in _CONTROL_PRIMS)
        assert n_access > 0, f"{name}: census saw no access primitives"
        assert n_control == 0, (
            f"{name} posmap lookup contains data-dependent control flow "
            f"({ {p: base[p] for p in _CONTROL_PRIMS if base[p]} }) — a "
            "run-time predicate could skip accesses under a fixed trace"
        )
        out[name] = {
            "accesses": n_access,
            "gathers": base["gather"],
            "scatters": sum(
                v for k, v in base.items() if k.startswith("scatter")
            ),
            "census_size": sum(base.values()),
        }
        if verbose:
            print(f"{name}: {out[name]}")

    # positive control: the audit distinguishes the two schedules
    assert out["recursive"]["accesses"] > out["flat"]["accesses"], (
        "positive control failed: the recursive lookup's access census "
        f"({out['recursive']}) does not exceed the flat one's "
        f"({out['flat']}) — the census is not seeing the internal ORAM"
    )
    return out


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--batch", type=int, default=16)
    args = ap.parse_args(argv)
    for occ, srt in (("dense", "xla"), ("scan", "xla"), ("scan", "radix")):
        out = check_posmap_access_schedule(
            b=args.batch, occ_impl=occ, sort_impl=srt, verbose=True
        )
        print(f"[check_posmap_oblivious] occ={occ} sort={srt}: OK {out}")
    print("[check_posmap_oblivious] PASS: position-map access schedule "
          "is a constant of the geometry")
    return 0


if __name__ == "__main__":
    sys.exit(main())
