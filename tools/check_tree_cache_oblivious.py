#!/usr/bin/env python
"""CI gate: the tree-top-cached round is index-blind AND actually cuts
the per-access HBM path traffic to the bottom path_len−k levels.

Two claims, both jaxpr-level (the PR-3/5/7 audit pattern — trace-time
facts, not runtime sampling):

1. **Index-independence.** Trace ``oram_round`` with the batch indices
   baked in as concrete constants for adversarially different index
   sets (all distinct, all identical, all dummy, mixed duplicates) and
   assert the full primitive census is IDENTICAL across them, with no
   data-dependent control flow anywhere. The tree-top cache moves the
   top k levels into private cache planes — this proves the move never
   introduces an index-dependent shortcut (e.g. skipping the cache
   concat for dummy batches).

2. **HBM row-count accounting.** Every gather/scatter whose operand is
   one of the big HBM tree planes (``tree_idx`` u32[n·Z], ``tree_val``
   u32[n, Z·V], ``nonces`` u32[n, 2], ``tree_leaf`` u32[n·Z]) must move
   exactly ``B·(path_len−k)`` bucket rows (``·Z`` slots for the flat
   slot planes) — i.e. per access, exactly ``path_len−k`` bucket rows
   per plane, the ISSUE-8 acceptance number. ``k=0`` is the positive
   control: the same census shows the full ``path_len`` rows, proving
   the counter sees the traffic it claims to cut. The cache planes must
   appear in the census at ``k>0`` (the top levels are really served
   from the cache) and must be absent at ``k=0``.

Wired into tier-1 via tests/test_tree_cache.py; standalone:
``python tools/check_tree_cache_oblivious.py``.

Since ISSUE 12 the equation walk / census / plane row accounting live in
the shared analyzer core (grapevine_tpu/analysis/jaxpr_walk.py) — this
tool, the posmap gate, and the taint analyzer cannot drift. CLI and
exit codes are unchanged. ISSUE 12 also closed a matrix gap: the
``k=0, posmap_impl=recursive`` cell now has its own always-on census
(:func:`check_k0_recursive_census`) instead of riding only the heavy
``-m slow`` recursive audit.
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from grapevine_tpu.analysis.jaxpr_walk import (  # noqa: E402
    ACCESS_PRIMS as _ACCESS_PRIMS,  # noqa: F401 - part of the gate's API
    CONTROL_PRIMS as _CONTROL_PRIMS,
    census as _census,
    plane_rows as _shared_plane_rows,
)


def _index_sets(cfg, b: int):
    import numpy as np

    rng = np.random.default_rng(11)
    return {
        "distinct": (np.arange(b) % cfg.blocks).astype(np.uint32),
        "all_same": np.zeros(b, np.uint32),
        "all_dummy": np.full(b, cfg.dummy_index, np.uint32),
        "mixed_dups": rng.integers(0, cfg.blocks + 1, b).astype(np.uint32),
    }


def _trace_round(cfg, idxs, b: int):
    """Jaxpr of one whole ORAM round with ``idxs`` concrete constants."""
    import jax
    import jax.numpy as jnp

    from grapevine_tpu.oram.path_oram import init_oram
    from grapevine_tpu.oram.round import oram_round

    state = jax.eval_shape(lambda: init_oram(cfg, jax.random.PRNGKey(0)))
    cidxs = jnp.asarray(idxs)
    recursive = cfg.posmap is not None

    def apply_batch(vals0, present0):
        return jnp.sum(vals0, axis=1), vals0, present0

    u32 = jnp.uint32
    lf = jax.ShapeDtypeStruct((b,), u32)

    def run(st, nl, dl, pm_nl, pm_dl):
        return oram_round(
            cfg, st, cidxs, nl, dl, apply_batch,
            pm_new_leaves=pm_nl if recursive else None,
            pm_dummy_leaves=pm_dl if recursive else None,
        )

    return jax.make_jaxpr(run)(state, lf, lf, lf, lf)


def _tree_planes(cfg) -> dict:
    """This geometry's HBM tree planes (and cache planes at k>0) in the
    shared ``plane_rows`` declaration format: name -> (shape, divisor)."""
    z, v = cfg.bucket_slots, cfg.value_words
    n = cfg.n_buckets_padded
    cb = cfg.cache_buckets
    # tree_idx/tree_leaf are stored flat [n·Z] but fetched/written
    # through bucket-axis [n, Z] reshape views since ISSUE 14 (the u32
    # certified-geometry refactor), so the gather/scatter operands the
    # accounting matches on are the 2-D views at divisor 1
    planes = {
        "tree_idx": ((n, z), 1),
        "tree_val": ((n, z * v), 1),
        "nonces": ((n, 2), 1),
    }
    if cfg.posmap is not None:
        planes["tree_leaf"] = ((n, z), 1)
    if cb:
        planes["cache_idx"] = ((cb * z,), z)
        planes["cache_val"] = ((cb, z * v), 1)
        if cfg.posmap is not None:
            planes["cache_leaf"] = ((cb * z,), z)
    return planes


def _plane_rows(jaxpr, cfg) -> dict:
    """Rows moved per HBM tree plane (and cache plane): the shared
    analyzer core's accounting over this geometry's plane declarations."""
    return _shared_plane_rows(jaxpr, _tree_planes(cfg))


def check_tree_cache_schedule(
    b: int = 8, height: int = 5, verbose: bool = False, recursive: bool = False
) -> dict:
    """Run both audits over k ∈ {0, 2}; raises AssertionError on any
    violation, returns the per-k row accounting."""
    from grapevine_tpu.oram.path_oram import OramConfig
    from grapevine_tpu.oram.posmap import derive_posmap_spec

    out = {}
    for k in (0, 2):
        pm = (
            derive_posmap_spec(1 << height, top_cache_levels=k)
            if recursive
            else None
        )
        cfg = OramConfig(
            height=height, value_words=8, n_blocks=1 << height,
            cipher_rounds=8, top_cache_levels=k, posmap=pm,
        )
        plen = cfg.path_len
        want = b * (plen - k)

        # -- 1. index-independence ---------------------------------------
        censuses = {
            iname: _census(_trace_round(cfg, idxs, b))
            for iname, idxs in _index_sets(cfg, b).items()
        }
        base_name, base = next(iter(censuses.items()))
        for iname, c in censuses.items():
            assert c == base, (
                f"k={k}: cached round traces a DIFFERENT program for "
                f"index set {iname!r} vs {base_name!r}: "
                f"{(c - base) + (base - c)} — the access schedule "
                "depends on the queried indices"
            )
        n_control = sum(base[p] for p in _CONTROL_PRIMS)
        assert n_control == 0, (
            f"k={k}: data-dependent control flow in the round "
            f"({ {p: base[p] for p in _CONTROL_PRIMS if base[p]} })"
        )

        # -- 2. HBM row accounting ---------------------------------------
        rows = _plane_rows(_trace_round(cfg, _index_sets(cfg, b)["mixed_dups"], b), cfg)
        for pname in ("tree_idx", "tree_val", "nonces"):
            moved = rows[pname]
            assert moved, f"k={k}: no accesses seen on {pname}"
            bad = [r for _, r in moved if r != want]
            assert not bad, (
                f"k={k}: {pname} moves {sorted(set(bad))} bucket rows "
                f"per round — every HBM path access must move exactly "
                f"B·(path_len−k) = {b}·({plen}−{k}) = {want}"
            )
        if recursive:
            assert rows["tree_leaf"], f"k={k}: no tree_leaf accesses"
            assert all(r == want for _, r in rows["tree_leaf"]), (
                f"k={k}: tree_leaf rows diverge from {want}"
            )
        if k:
            for pname in ("cache_idx", "cache_val"):
                assert rows[pname], (
                    f"k={k}: the cache plane {pname} is never accessed — "
                    "the cached levels are not actually served from the "
                    "cache"
                )
                assert all(r == b * k for _, r in rows[pname]), (
                    f"k={k}: {pname} moves {rows[pname]} — want B·k = "
                    f"{b * k} rows"
                )
        out[f"k{k}"] = {
            p: sorted({r for _, r in rs}) for p, rs in rows.items() if rs
        }
        if verbose:
            print(f"k={k} ({'recursive' if recursive else 'flat'}): "
                  f"{out[f'k{k}']}")

    # positive control across k: the counter must SEE the cut
    full = out["k0"]["tree_val"][0]
    cut = out["k2"]["tree_val"][0]
    assert full == b * (height + 1) and cut == b * (height - 1), (
        f"positive control failed: k=0 moves {full} rows, k=2 moves "
        f"{cut} — expected {b * (height + 1)} vs {b * (height - 1)}"
    )
    return out


def check_k0_recursive_census(b: int = 4, height: int = 5) -> dict:
    """The matrix cell the pre-ISSUE-12 wiring missed: ``k=0`` with
    ``posmap_impl=recursive``.

    Tier-1 ran the full two-claim audit flat-only (the recursive variant
    rode ``-m slow``), so the uncached-recursive round — the exact
    program a `--posmap-impl recursive --tree-top-cache-levels 0` server
    runs — had no always-on index-blindness census. This runs claim 1
    (identical census across adversarial index sets, zero data-dependent
    control flow) plus the tree_leaf-plane row accounting for that one
    cell at a deliberately small geometry; returns the per-plane rows."""
    from grapevine_tpu.oram.path_oram import OramConfig
    from grapevine_tpu.oram.posmap import derive_posmap_spec

    cfg = OramConfig(
        height=height, value_words=8, n_blocks=1 << height,
        cipher_rounds=8, top_cache_levels=0,
        posmap=derive_posmap_spec(1 << height, top_cache_levels=0),
    )
    censuses = {
        iname: _census(_trace_round(cfg, idxs, b))
        for iname, idxs in _index_sets(cfg, b).items()
    }
    base_name, base = next(iter(censuses.items()))
    for iname, c in censuses.items():
        assert c == base, (
            f"k=0 recursive round traces a DIFFERENT program for index "
            f"set {iname!r} vs {base_name!r}: {(c - base) + (base - c)}"
        )
    n_control = sum(base[p] for p in _CONTROL_PRIMS)
    assert n_control == 0, (
        f"k=0 recursive: data-dependent control flow "
        f"({ {p: base[p] for p in _CONTROL_PRIMS if base[p]} })"
    )
    rows = _plane_rows(
        _trace_round(cfg, _index_sets(cfg, b)["mixed_dups"], b), cfg
    )
    want = b * cfg.path_len  # k=0: the full path on every plane
    for pname in ("tree_idx", "tree_val", "nonces", "tree_leaf"):
        moved = rows[pname]
        assert moved, f"k=0 recursive: no accesses seen on {pname}"
        bad = [r for _, r in moved if r != want]
        assert not bad, (
            f"k=0 recursive: {pname} moves {sorted(set(bad))} rows — "
            f"want the full B*path_len = {want}"
        )
    assert "cache_idx" not in rows, "k=0 must declare no cache planes"
    return {p: sorted({r for _, r in rs}) for p, rs in rows.items() if rs}


def _evict_cfg(b: int, height: int, k: int, window: int,
               recursive: bool = False):
    from grapevine_tpu.oram.path_oram import OramConfig
    from grapevine_tpu.oram.posmap import derive_posmap_spec

    pm = (
        derive_posmap_spec(1 << height, top_cache_levels=k,
                           evict_window=window, evict_fetch_count=b)
        if recursive
        else None
    )
    return OramConfig(
        height=height, value_words=8, n_blocks=1 << height,
        cipher_rounds=8, top_cache_levels=k, posmap=pm,
        evict_window=window, evict_fetch_count=b,
        evict_buffer_slots=4 * b * window,
    )


def check_evict_round_accounting(
    b: int = 8, height: int = 7, k: int = 2, window: int = 2,
    verbose: bool = False, recursive: bool = False,
) -> dict:
    """The delayed-eviction (PR 15) extension of this gate: the E-round
    schedule's HBM row accounting, trace-level.

    Three claims over one ``evict_window = E`` geometry:

    1. **Fetch rounds are read-only on HBM.** The fetch-only round's
       census is identical across adversarial index sets (index-blind,
       claim 1 of the per-round audit), its tree-plane GATHERS move
       exactly ``B·(path_len−k)`` bucket rows per plane — the same
       fetch traffic as the E=1 round — and it contains ZERO scatters
       on any tree/nonce/cache plane: the scatter+encrypt half of the
       round is really gone from the steady state.
    2. **The flush writes exactly the window, deduplicated.** One
       ``oram_flush`` scatters exactly ``flush_target_slots =
       min(E·B·path_len, n_buckets_padded)`` bucket rows per plane —
       the union of the window's fetched paths written ONCE each
       (write transcript ≡ the deduplicated union of the window's read
       transcripts; the ``min`` is the amortization: past tree
       saturation, extra window rounds add fetch traffic but no write
       traffic) — with ZERO tree-plane gathers (the live rows were
       already pulled into the buffer at fetch time). Cache planes see
       the same ``t``-row shape at k>0 (cached targets peel off by the
       heap-prefix mask).
    3. **Recipient-independence of the cadence.** Both programs trace
       with the batch indices baked in as constants; identical censuses
       across index sets plus a bucket-target set that is a pure
       function of the (public) leaves means nothing about which
       recipients were touched can move a row or a flush.

    Returns the per-program row accounting.
    """
    from grapevine_tpu.oram.round import flush_target_slots

    cfg = _evict_cfg(b, height, k, window, recursive)
    plen = cfg.path_len
    want_fetch = b * (plen - k)
    want_flush = flush_target_slots(cfg)
    # the audit needs the UNSATURATED dedup regime: at t =
    # n_buckets_padded the compacted output planes coincide in shape
    # with the HBM tree planes and shape-based attribution would count
    # private scatters as tree traffic (a false positive, not a leak).
    # The saturated cap is pure arithmetic, pinned below.
    assert want_flush < cfg.n_buckets_padded, (
        "audit geometry must keep the flush target set unsaturated "
        f"(t={want_flush} vs n_buckets_padded={cfg.n_buckets_padded}) — "
        "raise height or lower window/batch"
    )
    # the saturation clamp itself (the amortization bound): arithmetic,
    # no trace needed
    sat = _evict_cfg(b, 3, 0, 8, False)
    assert flush_target_slots(sat) == sat.n_buckets_padded

    # -- 1. fetch round: index-blind + read-only ------------------------
    censuses = {
        iname: _census(_trace_round(cfg, idxs, b))
        for iname, idxs in _index_sets(cfg, b).items()
    }
    base_name, base = next(iter(censuses.items()))
    for iname, c in censuses.items():
        assert c == base, (
            f"E={window}: fetch round traces a DIFFERENT program for "
            f"index set {iname!r} vs {base_name!r}: "
            f"{(c - base) + (base - c)}"
        )
    n_control = sum(base[p] for p in _CONTROL_PRIMS)
    assert n_control == 0, (
        f"E={window}: data-dependent control flow in the fetch round "
        f"({ {p: base[p] for p in _CONTROL_PRIMS if base[p]} })"
    )
    rows = _plane_rows(
        _trace_round(cfg, _index_sets(cfg, b)["mixed_dups"], b), cfg
    )
    fetch_acct = {}
    tree_planes = ["tree_idx", "tree_val", "nonces"]
    if recursive:
        tree_planes.append("tree_leaf")
    for pname in tree_planes:
        moved = rows[pname]
        gathers = [r for op, r in moved if op == "gather"]
        scatters = [(op, r) for op, r in moved if op != "gather"]
        assert not scatters, (
            f"E={window}: fetch round SCATTERS to {pname} ({scatters}) "
            "— the steady-state round must be read-only on the HBM tree"
        )
        if pname != "nonces" or cfg.encrypted:
            assert gathers and all(r == want_fetch for r in gathers), (
                f"E={window}: {pname} fetch gathers move "
                f"{sorted(set(gathers))} rows — want exactly "
                f"B·(path_len−k) = {want_fetch}"
            )
        fetch_acct[pname] = sorted(set(gathers))
    if k:
        for pname in ("cache_idx", "cache_val"):
            moved = rows[pname]
            assert all(op == "gather" for op, _ in moved), (
                f"E={window}: fetch round writes the cache plane "
                f"{pname} — cached levels flush with everything else"
            )

    # -- 2. flush: writes exactly the window, reads nothing -------------
    import jax

    from grapevine_tpu.oram.path_oram import init_oram
    from grapevine_tpu.oram.round import oram_flush

    state = jax.eval_shape(lambda: init_oram(cfg, jax.random.PRNGKey(0)))
    fl_jaxpr = jax.make_jaxpr(lambda st: oram_flush(cfg, st))(state)
    frows = _shared_plane_rows(fl_jaxpr, _tree_planes(cfg))
    flush_acct = {}
    for pname in tree_planes:
        moved = frows[pname]
        gathers = [r for op, r in moved if op == "gather"]
        scatters = [r for op, r in moved if op != "gather"]
        assert not gathers, (
            f"E={window}: flush GATHERS from {pname} — the window's "
            "live rows were already pulled into the buffer at fetch "
            "time; a flush-time read is a second, unaccounted pass"
        )
        if pname != "nonces" or cfg.encrypted:
            assert scatters and all(r == want_flush for r in scatters), (
                f"E={window}: {pname} flush scatters move "
                f"{sorted(set(scatters))} rows — want exactly "
                f"flush_target_slots = min(E·B·path_len, "
                f"n_buckets_padded) = {want_flush}"
            )
        flush_acct[pname] = sorted(set(scatters))
    if k:
        # recursive geometries: the INNER tree's cache planes share the
        # outer cache planes' shape (both (2^k−1)·Z), so shape-based
        # attribution folds the inner flush's cache writes in — accept
        # the inner t-row shape alongside the outer one
        want_cache = {want_flush}
        if recursive:
            from grapevine_tpu.oram.posmap import inner_oram_config

            want_cache.add(flush_target_slots(inner_oram_config(cfg.posmap)))
        for pname in ("cache_idx", "cache_val"):
            moved = frows[pname]
            scatters = [r for op, r in moved if op != "gather"]
            assert scatters and set(scatters) <= want_cache and (
                want_flush in scatters
            ), (
                f"E={window}: cache plane {pname} flush moves "
                f"{moved} — want the t-row target shape(s) {want_cache}"
            )
    out = {"fetch": fetch_acct, "flush": flush_acct,
           "want_fetch_rows": want_fetch, "want_flush_rows": want_flush}
    if verbose:
        print(f"E={window} k={k} "
              f"({'recursive' if recursive else 'flat'}): {out}")
    return out


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--height", type=int, default=5)
    args = ap.parse_args(argv)
    for recursive in (False, True):
        out = check_tree_cache_schedule(
            b=args.batch, height=args.height, verbose=True,
            recursive=recursive,
        )
        print(f"[check_tree_cache_oblivious] recursive={recursive}: OK {out}")
    out = check_k0_recursive_census(b=4, height=5)
    print(f"[check_tree_cache_oblivious] k0-recursive cell: OK {out}")
    for recursive in (False, True):
        out = check_evict_round_accounting(verbose=True,
                                           recursive=recursive)
        print(f"[check_tree_cache_oblivious] evict schedule "
              f"(recursive={recursive}): OK")
    print("[check_tree_cache_oblivious] PASS: cached round is index-blind "
          "and HBM path traffic is exactly B·(path_len−k) rows per plane; "
          "delayed-eviction fetch rounds are HBM-read-only and each flush "
          "writes exactly the E-round window")
    return 0


if __name__ == "__main__":
    sys.exit(main())
