#!/usr/bin/env python
"""CI gate: the tree-top-cached round is index-blind AND actually cuts
the per-access HBM path traffic to the bottom path_len−k levels.

Two claims, both jaxpr-level (the PR-3/5/7 audit pattern — trace-time
facts, not runtime sampling):

1. **Index-independence.** Trace ``oram_round`` with the batch indices
   baked in as concrete constants for adversarially different index
   sets (all distinct, all identical, all dummy, mixed duplicates) and
   assert the full primitive census is IDENTICAL across them, with no
   data-dependent control flow anywhere. The tree-top cache moves the
   top k levels into private cache planes — this proves the move never
   introduces an index-dependent shortcut (e.g. skipping the cache
   concat for dummy batches).

2. **HBM row-count accounting.** Every gather/scatter whose operand is
   one of the big HBM tree planes (``tree_idx`` u32[n·Z], ``tree_val``
   u32[n, Z·V], ``nonces`` u32[n, 2], ``tree_leaf`` u32[n·Z]) must move
   exactly ``B·(path_len−k)`` bucket rows (``·Z`` slots for the flat
   slot planes) — i.e. per access, exactly ``path_len−k`` bucket rows
   per plane, the ISSUE-8 acceptance number. ``k=0`` is the positive
   control: the same census shows the full ``path_len`` rows, proving
   the counter sees the traffic it claims to cut. The cache planes must
   appear in the census at ``k>0`` (the top levels are really served
   from the cache) and must be absent at ``k=0``.

Wired into tier-1 via tests/test_tree_cache.py; standalone:
``python tools/check_tree_cache_oblivious.py``.

Since ISSUE 12 the equation walk / census / plane row accounting live in
the shared analyzer core (grapevine_tpu/analysis/jaxpr_walk.py) — this
tool, the posmap gate, and the taint analyzer cannot drift. CLI and
exit codes are unchanged. ISSUE 12 also closed a matrix gap: the
``k=0, posmap_impl=recursive`` cell now has its own always-on census
(:func:`check_k0_recursive_census`) instead of riding only the heavy
``-m slow`` recursive audit.
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from grapevine_tpu.analysis.jaxpr_walk import (  # noqa: E402
    ACCESS_PRIMS as _ACCESS_PRIMS,  # noqa: F401 - part of the gate's API
    CONTROL_PRIMS as _CONTROL_PRIMS,
    census as _census,
    plane_rows as _shared_plane_rows,
)


def _index_sets(cfg, b: int):
    import numpy as np

    rng = np.random.default_rng(11)
    return {
        "distinct": (np.arange(b) % cfg.blocks).astype(np.uint32),
        "all_same": np.zeros(b, np.uint32),
        "all_dummy": np.full(b, cfg.dummy_index, np.uint32),
        "mixed_dups": rng.integers(0, cfg.blocks + 1, b).astype(np.uint32),
    }


def _trace_round(cfg, idxs, b: int):
    """Jaxpr of one whole ORAM round with ``idxs`` concrete constants."""
    import jax
    import jax.numpy as jnp

    from grapevine_tpu.oram.path_oram import init_oram
    from grapevine_tpu.oram.round import oram_round

    state = jax.eval_shape(lambda: init_oram(cfg, jax.random.PRNGKey(0)))
    cidxs = jnp.asarray(idxs)
    recursive = cfg.posmap is not None

    def apply_batch(vals0, present0):
        return jnp.sum(vals0, axis=1), vals0, present0

    u32 = jnp.uint32
    lf = jax.ShapeDtypeStruct((b,), u32)

    def run(st, nl, dl, pm_nl, pm_dl):
        return oram_round(
            cfg, st, cidxs, nl, dl, apply_batch,
            pm_new_leaves=pm_nl if recursive else None,
            pm_dummy_leaves=pm_dl if recursive else None,
        )

    return jax.make_jaxpr(run)(state, lf, lf, lf, lf)


def _tree_planes(cfg) -> dict:
    """This geometry's HBM tree planes (and cache planes at k>0) in the
    shared ``plane_rows`` declaration format: name -> (shape, divisor)."""
    z, v = cfg.bucket_slots, cfg.value_words
    n = cfg.n_buckets_padded
    cb = cfg.cache_buckets
    # tree_idx/tree_leaf are stored flat [n·Z] but fetched/written
    # through bucket-axis [n, Z] reshape views since ISSUE 14 (the u32
    # certified-geometry refactor), so the gather/scatter operands the
    # accounting matches on are the 2-D views at divisor 1
    planes = {
        "tree_idx": ((n, z), 1),
        "tree_val": ((n, z * v), 1),
        "nonces": ((n, 2), 1),
    }
    if cfg.posmap is not None:
        planes["tree_leaf"] = ((n, z), 1)
    if cb:
        planes["cache_idx"] = ((cb * z,), z)
        planes["cache_val"] = ((cb, z * v), 1)
        if cfg.posmap is not None:
            planes["cache_leaf"] = ((cb * z,), z)
    return planes


def _plane_rows(jaxpr, cfg) -> dict:
    """Rows moved per HBM tree plane (and cache plane): the shared
    analyzer core's accounting over this geometry's plane declarations."""
    return _shared_plane_rows(jaxpr, _tree_planes(cfg))


def check_tree_cache_schedule(
    b: int = 8, height: int = 5, verbose: bool = False, recursive: bool = False
) -> dict:
    """Run both audits over k ∈ {0, 2}; raises AssertionError on any
    violation, returns the per-k row accounting."""
    from grapevine_tpu.oram.path_oram import OramConfig
    from grapevine_tpu.oram.posmap import derive_posmap_spec

    out = {}
    for k in (0, 2):
        pm = (
            derive_posmap_spec(1 << height, top_cache_levels=k)
            if recursive
            else None
        )
        cfg = OramConfig(
            height=height, value_words=8, n_blocks=1 << height,
            cipher_rounds=8, top_cache_levels=k, posmap=pm,
        )
        plen = cfg.path_len
        want = b * (plen - k)

        # -- 1. index-independence ---------------------------------------
        censuses = {
            iname: _census(_trace_round(cfg, idxs, b))
            for iname, idxs in _index_sets(cfg, b).items()
        }
        base_name, base = next(iter(censuses.items()))
        for iname, c in censuses.items():
            assert c == base, (
                f"k={k}: cached round traces a DIFFERENT program for "
                f"index set {iname!r} vs {base_name!r}: "
                f"{(c - base) + (base - c)} — the access schedule "
                "depends on the queried indices"
            )
        n_control = sum(base[p] for p in _CONTROL_PRIMS)
        assert n_control == 0, (
            f"k={k}: data-dependent control flow in the round "
            f"({ {p: base[p] for p in _CONTROL_PRIMS if base[p]} })"
        )

        # -- 2. HBM row accounting ---------------------------------------
        rows = _plane_rows(_trace_round(cfg, _index_sets(cfg, b)["mixed_dups"], b), cfg)
        for pname in ("tree_idx", "tree_val", "nonces"):
            moved = rows[pname]
            assert moved, f"k={k}: no accesses seen on {pname}"
            bad = [r for _, r in moved if r != want]
            assert not bad, (
                f"k={k}: {pname} moves {sorted(set(bad))} bucket rows "
                f"per round — every HBM path access must move exactly "
                f"B·(path_len−k) = {b}·({plen}−{k}) = {want}"
            )
        if recursive:
            assert rows["tree_leaf"], f"k={k}: no tree_leaf accesses"
            assert all(r == want for _, r in rows["tree_leaf"]), (
                f"k={k}: tree_leaf rows diverge from {want}"
            )
        if k:
            for pname in ("cache_idx", "cache_val"):
                assert rows[pname], (
                    f"k={k}: the cache plane {pname} is never accessed — "
                    "the cached levels are not actually served from the "
                    "cache"
                )
                assert all(r == b * k for _, r in rows[pname]), (
                    f"k={k}: {pname} moves {rows[pname]} — want B·k = "
                    f"{b * k} rows"
                )
        out[f"k{k}"] = {
            p: sorted({r for _, r in rs}) for p, rs in rows.items() if rs
        }
        if verbose:
            print(f"k={k} ({'recursive' if recursive else 'flat'}): "
                  f"{out[f'k{k}']}")

    # positive control across k: the counter must SEE the cut
    full = out["k0"]["tree_val"][0]
    cut = out["k2"]["tree_val"][0]
    assert full == b * (height + 1) and cut == b * (height - 1), (
        f"positive control failed: k=0 moves {full} rows, k=2 moves "
        f"{cut} — expected {b * (height + 1)} vs {b * (height - 1)}"
    )
    return out


def check_k0_recursive_census(b: int = 4, height: int = 5) -> dict:
    """The matrix cell the pre-ISSUE-12 wiring missed: ``k=0`` with
    ``posmap_impl=recursive``.

    Tier-1 ran the full two-claim audit flat-only (the recursive variant
    rode ``-m slow``), so the uncached-recursive round — the exact
    program a `--posmap-impl recursive --tree-top-cache-levels 0` server
    runs — had no always-on index-blindness census. This runs claim 1
    (identical census across adversarial index sets, zero data-dependent
    control flow) plus the tree_leaf-plane row accounting for that one
    cell at a deliberately small geometry; returns the per-plane rows."""
    from grapevine_tpu.oram.path_oram import OramConfig
    from grapevine_tpu.oram.posmap import derive_posmap_spec

    cfg = OramConfig(
        height=height, value_words=8, n_blocks=1 << height,
        cipher_rounds=8, top_cache_levels=0,
        posmap=derive_posmap_spec(1 << height, top_cache_levels=0),
    )
    censuses = {
        iname: _census(_trace_round(cfg, idxs, b))
        for iname, idxs in _index_sets(cfg, b).items()
    }
    base_name, base = next(iter(censuses.items()))
    for iname, c in censuses.items():
        assert c == base, (
            f"k=0 recursive round traces a DIFFERENT program for index "
            f"set {iname!r} vs {base_name!r}: {(c - base) + (base - c)}"
        )
    n_control = sum(base[p] for p in _CONTROL_PRIMS)
    assert n_control == 0, (
        f"k=0 recursive: data-dependent control flow "
        f"({ {p: base[p] for p in _CONTROL_PRIMS if base[p]} })"
    )
    rows = _plane_rows(
        _trace_round(cfg, _index_sets(cfg, b)["mixed_dups"], b), cfg
    )
    want = b * cfg.path_len  # k=0: the full path on every plane
    for pname in ("tree_idx", "tree_val", "nonces", "tree_leaf"):
        moved = rows[pname]
        assert moved, f"k=0 recursive: no accesses seen on {pname}"
        bad = [r for _, r in moved if r != want]
        assert not bad, (
            f"k=0 recursive: {pname} moves {sorted(set(bad))} rows — "
            f"want the full B*path_len = {want}"
        )
    assert "cache_idx" not in rows, "k=0 must declare no cache planes"
    return {p: sorted({r for _, r in rs}) for p, rs in rows.items() if rs}


def _evict_cfg(b: int, height: int, k: int, window: int,
               recursive: bool = False):
    from grapevine_tpu.oram.path_oram import OramConfig
    from grapevine_tpu.oram.posmap import derive_posmap_spec

    pm = (
        derive_posmap_spec(1 << height, top_cache_levels=k,
                           evict_window=window, evict_fetch_count=b)
        if recursive
        else None
    )
    return OramConfig(
        height=height, value_words=8, n_blocks=1 << height,
        cipher_rounds=8, top_cache_levels=k, posmap=pm,
        evict_window=window, evict_fetch_count=b,
        evict_buffer_slots=4 * b * window,
    )


def check_evict_round_accounting(
    b: int = 8, height: int = 7, k: int = 2, window: int = 2,
    verbose: bool = False, recursive: bool = False,
) -> dict:
    """The delayed-eviction (PR 15) extension of this gate: the E-round
    schedule's HBM row accounting, trace-level.

    Three claims over one ``evict_window = E`` geometry:

    1. **Fetch rounds are read-only on HBM.** The fetch-only round's
       census is identical across adversarial index sets (index-blind,
       claim 1 of the per-round audit), its tree-plane GATHERS move
       exactly ``B·(path_len−k)`` bucket rows per plane — the same
       fetch traffic as the E=1 round — and it contains ZERO scatters
       on any tree/nonce/cache plane: the scatter+encrypt half of the
       round is really gone from the steady state.
    2. **The flush writes exactly the window, deduplicated.** One
       ``oram_flush`` scatters exactly ``flush_target_slots =
       min(E·B·path_len, n_buckets_padded)`` bucket rows per plane —
       the union of the window's fetched paths written ONCE each
       (write transcript ≡ the deduplicated union of the window's read
       transcripts; the ``min`` is the amortization: past tree
       saturation, extra window rounds add fetch traffic but no write
       traffic) — with ZERO tree-plane gathers (the live rows were
       already pulled into the buffer at fetch time). Cache planes see
       the same ``t``-row shape at k>0 (cached targets peel off by the
       heap-prefix mask).
    3. **Recipient-independence of the cadence.** Both programs trace
       with the batch indices baked in as constants; identical censuses
       across index sets plus a bucket-target set that is a pure
       function of the (public) leaves means nothing about which
       recipients were touched can move a row or a flush.

    Returns the per-program row accounting.
    """
    from grapevine_tpu.oram.round import flush_target_slots

    cfg = _evict_cfg(b, height, k, window, recursive)
    plen = cfg.path_len
    want_fetch = b * (plen - k)
    want_flush = flush_target_slots(cfg)
    # the audit needs the UNSATURATED dedup regime: at t =
    # n_buckets_padded the compacted output planes coincide in shape
    # with the HBM tree planes and shape-based attribution would count
    # private scatters as tree traffic (a false positive, not a leak).
    # The saturated cap is pure arithmetic, pinned below.
    assert want_flush < cfg.n_buckets_padded, (
        "audit geometry must keep the flush target set unsaturated "
        f"(t={want_flush} vs n_buckets_padded={cfg.n_buckets_padded}) — "
        "raise height or lower window/batch"
    )
    # the saturation clamp itself (the amortization bound): arithmetic,
    # no trace needed
    sat = _evict_cfg(b, 3, 0, 8, False)
    assert flush_target_slots(sat) == sat.n_buckets_padded

    # -- 1. fetch round: index-blind + read-only ------------------------
    censuses = {
        iname: _census(_trace_round(cfg, idxs, b))
        for iname, idxs in _index_sets(cfg, b).items()
    }
    base_name, base = next(iter(censuses.items()))
    for iname, c in censuses.items():
        assert c == base, (
            f"E={window}: fetch round traces a DIFFERENT program for "
            f"index set {iname!r} vs {base_name!r}: "
            f"{(c - base) + (base - c)}"
        )
    n_control = sum(base[p] for p in _CONTROL_PRIMS)
    assert n_control == 0, (
        f"E={window}: data-dependent control flow in the fetch round "
        f"({ {p: base[p] for p in _CONTROL_PRIMS if base[p]} })"
    )
    rows = _plane_rows(
        _trace_round(cfg, _index_sets(cfg, b)["mixed_dups"], b), cfg
    )
    fetch_acct = {}
    tree_planes = ["tree_idx", "tree_val", "nonces"]
    if recursive:
        tree_planes.append("tree_leaf")
    for pname in tree_planes:
        moved = rows[pname]
        gathers = [r for op, r in moved if op == "gather"]
        scatters = [(op, r) for op, r in moved if op != "gather"]
        assert not scatters, (
            f"E={window}: fetch round SCATTERS to {pname} ({scatters}) "
            "— the steady-state round must be read-only on the HBM tree"
        )
        if pname != "nonces" or cfg.encrypted:
            assert gathers and all(r == want_fetch for r in gathers), (
                f"E={window}: {pname} fetch gathers move "
                f"{sorted(set(gathers))} rows — want exactly "
                f"B·(path_len−k) = {want_fetch}"
            )
        fetch_acct[pname] = sorted(set(gathers))
    if k:
        for pname in ("cache_idx", "cache_val"):
            moved = rows[pname]
            assert all(op == "gather" for op, _ in moved), (
                f"E={window}: fetch round writes the cache plane "
                f"{pname} — cached levels flush with everything else"
            )

    # -- 2. flush: writes exactly the window, reads nothing -------------
    import jax

    from grapevine_tpu.oram.path_oram import init_oram
    from grapevine_tpu.oram.round import oram_flush

    state = jax.eval_shape(lambda: init_oram(cfg, jax.random.PRNGKey(0)))
    fl_jaxpr = jax.make_jaxpr(lambda st: oram_flush(cfg, st))(state)
    frows = _shared_plane_rows(fl_jaxpr, _tree_planes(cfg))
    flush_acct = {}
    for pname in tree_planes:
        moved = frows[pname]
        gathers = [r for op, r in moved if op == "gather"]
        scatters = [r for op, r in moved if op != "gather"]
        assert not gathers, (
            f"E={window}: flush GATHERS from {pname} — the window's "
            "live rows were already pulled into the buffer at fetch "
            "time; a flush-time read is a second, unaccounted pass"
        )
        if pname != "nonces" or cfg.encrypted:
            assert scatters and all(r == want_flush for r in scatters), (
                f"E={window}: {pname} flush scatters move "
                f"{sorted(set(scatters))} rows — want exactly "
                f"flush_target_slots = min(E·B·path_len, "
                f"n_buckets_padded) = {want_flush}"
            )
        flush_acct[pname] = sorted(set(scatters))
    if k:
        # recursive geometries: the INNER tree's cache planes share the
        # outer cache planes' shape (both (2^k−1)·Z), so shape-based
        # attribution folds the inner flush's cache writes in — accept
        # the inner t-row shape alongside the outer one
        want_cache = {want_flush}
        if recursive:
            from grapevine_tpu.oram.posmap import inner_oram_config

            want_cache.add(flush_target_slots(inner_oram_config(cfg.posmap)))
        for pname in ("cache_idx", "cache_val"):
            moved = frows[pname]
            scatters = [r for op, r in moved if op != "gather"]
            assert scatters and set(scatters) <= want_cache and (
                want_flush in scatters
            ), (
                f"E={window}: cache plane {pname} flush moves "
                f"{moved} — want the t-row target shape(s) {want_cache}"
            )
    out = {"fetch": fetch_acct, "flush": flush_acct,
           "want_fetch_rows": want_fetch, "want_flush_rows": want_flush}
    if verbose:
        print(f"E={window} k={k} "
              f"({'recursive' if recursive else 'flat'}): {out}")
    return out


def _trace_sharded(cfg, what, mesh, idxs=None, b=0):
    """Jaxpr of one SHARDED fetch round or flush: the oram program wrapped
    in the same shard_map geometry the engine uses (parallel/mesh.py),
    so ``walk_eqns`` recurses into the shard body where every tree-plane
    operand carries its SHARD-LOCAL shape."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from grapevine_tpu.oram.path_oram import init_oram
    from grapevine_tpu.oram.round import oram_flush, oram_round
    from grapevine_tpu.parallel.mesh import (
        _SHARD_MAP_NOCHECK, TREE_AXIS, _oram_specs, _shard_map,
    )

    state = jax.eval_shape(lambda: init_oram(cfg, jax.random.PRNGKey(0)))
    specs = _oram_specs()
    if what == "flush":
        fn = _shard_map(
            lambda st: oram_flush(cfg, st, TREE_AXIS),
            mesh=mesh, in_specs=(specs,), out_specs=specs,
            **_SHARD_MAP_NOCHECK,
        )
        return jax.make_jaxpr(fn)(state)
    cidxs = jnp.asarray(idxs)
    recursive = cfg.posmap is not None

    def apply_batch(vals0, present0):
        return jnp.sum(vals0, axis=1), vals0, present0

    def run(st, nl, dl, pm_nl, pm_dl):
        return oram_round(
            cfg, st, cidxs, nl, dl, apply_batch, axis_name=TREE_AXIS,
            pm_new_leaves=pm_nl if recursive else None,
            pm_dummy_leaves=pm_dl if recursive else None,
        )

    lf = jax.ShapeDtypeStruct((b,), jnp.uint32)
    fn = _shard_map(
        run, mesh=mesh, in_specs=(specs, P(), P(), P(), P()),
        out_specs=(specs, P(), P()), **_SHARD_MAP_NOCHECK,
    )
    return jax.make_jaxpr(fn)(state, lf, lf, lf, lf)


def _local_tree_planes(cfg, n_shards: int) -> dict:
    """Shard-LOCAL plane declarations: the bucket axis shards as
    contiguous equal heap ranges, so each chip's tree/nonce operands are
    the full planes at ``n / n_shards`` rows; cache planes are
    replicated private state and keep their full shape."""
    planes = _tree_planes(cfg)
    out = {}
    for name, (shape, div) in planes.items():
        if name.startswith(("tree_", "nonces")):
            shape = (shape[0] // n_shards,) + tuple(shape[1:])
        out[name] = (shape, div)
    return out


def _unmasked_scatter_mutant(orig):
    """The seeded defect the sharded audit exists to catch: a sharded
    ``_path_scatter`` that keeps the dedup owner mask but DROPS the
    shard-ownership mask — every chip writes every target into its local
    plane at wrapped indices instead of dropping non-owned lanes, so the
    union across the mesh is no longer the single-chip flush."""
    import jax
    import jax.numpy as jnp

    def mutant(tree, path_b, new_vals, axis_name, owner=None):
        if axis_name is None:
            return orig(tree, path_b, new_vals, axis_name, owner)
        n_local = tree.shape[0]
        u32 = jnp.uint32
        base = (jax.lax.axis_index(axis_name) * n_local).astype(u32)
        loc = (path_b - base) % u32(n_local)  # wraps instead of dropping
        if owner is not None:
            loc = jnp.where(owner, loc, u32(n_local))
        return tree.at[loc].set(new_vals, mode="drop", unique_indices=True)

    return mutant


def check_sharded_evict_accounting(
    b: int = 6, height: int = 7, k: int = 2, window: int = 2,
    shards: int = 2, verbose: bool = False, recursive: bool = False,
    runtime: bool = True, _unmasked_scatter: bool = False,
) -> dict:
    """ISSUE-18 extension: the delayed-eviction schedule's accounting for
    the SHARDED program (parallel/mesh.py make_sharded_step/flush).

    Trace-level, per shard (walk_eqns recurses into the shard_map body,
    where operands carry shard-local shapes):

    1. **Per-shard fetch rounds are HBM-read-only at the uniform
       working-set shape.** Each chip's fetch round is index-blind
       (identical census across adversarial index sets, zero
       data-dependent control flow), its local tree-plane GATHER ops
       each carry exactly ``B·(path_len−k)`` rows — the full working-set
       shape, non-owned lanes masked, so per-chip row counts are a pure
       function of geometry, never of contents or ownership — and it
       contains ZERO scatters on any local tree/nonce plane.
    2. **Per-shard flush scatters carry exactly ``t`` rows.** Each
       chip's flush SCATTER ops carry all ``t = flush_target_slots``
       rows (the owner mask drops non-owned lanes via out-of-range
       targets — the static shape never shrinks), with ZERO local
       tree-plane gathers.

    Runtime, on a real mesh (the partition claim — where "sums to
    exactly the single-chip write set" lives):

    3. **Owner partition.** Running the window + flush sharded and
       single-chip from the same state: every bucket row the single-chip
       flush writes is written by EXACTLY ONE shard (its heap-range
       owner), the per-shard written-row counts sum to the single-chip
       count, and the assembled sharded state equals the single-chip
       state bit for bit.

    ``_unmasked_scatter=True`` seeds the control defect (shard mask
    dropped from the flush scatter) — the runtime partition check must
    FAIL; tests/test_evict.py pins both directions. ``runtime=False``
    runs only the (compile-free) trace claims — the always-on tier-1
    shape; the runtime partition + mutant ride ``-m slow`` and the
    standalone tool.
    """
    import jax

    from grapevine_tpu.oram.round import flush_target_slots
    from grapevine_tpu.parallel.mesh import make_mesh

    n_shards = min(shards, len(jax.devices()))
    mesh = make_mesh(jax.devices()[:n_shards])
    cfg = _evict_cfg(b, height, k, window, recursive)
    plen = cfg.path_len
    want_fetch = b * (plen - k)
    want_flush = flush_target_slots(cfg)
    n_local = cfg.n_buckets_padded // n_shards
    assert cfg.n_buckets_padded % n_shards == 0
    # shape-based attribution needs the local planes unambiguous: the
    # compacted flush working set is (t, ·) and the buffer is
    # (evict_buffer_slots, ·) — neither may coincide with a local tree
    # plane's (n/shards, ·) or private scatters count as tree traffic
    assert want_flush != n_local and cfg.evict_buffer_slots != n_local, (
        f"audit geometry ambiguity: t={want_flush} / buffer="
        f"{cfg.evict_buffer_slots} vs n_local={n_local} — pick b/height "
        "so the shard-local plane shape is unique"
    )

    # -- 1. per-shard fetch round: index-blind + read-only --------------
    censuses = {
        iname: _census(_trace_sharded(cfg, "round", mesh, idxs, b))
        for iname, idxs in _index_sets(cfg, b).items()
    }
    base_name, base = next(iter(censuses.items()))
    for iname, c in censuses.items():
        assert c == base, (
            f"shards={n_shards} E={window}: sharded fetch round traces "
            f"a DIFFERENT program for index set {iname!r} vs "
            f"{base_name!r}: {(c - base) + (base - c)}"
        )
    n_control = sum(base[p] for p in _CONTROL_PRIMS)
    assert n_control == 0, (
        f"shards={n_shards} E={window}: data-dependent control flow in "
        f"the sharded fetch round "
        f"({ {p: base[p] for p in _CONTROL_PRIMS if base[p]} })"
    )
    lplanes = _local_tree_planes(cfg, n_shards)
    rows = _shared_plane_rows(
        _trace_sharded(cfg, "round", mesh,
                       _index_sets(cfg, b)["mixed_dups"], b),
        lplanes,
    )
    tree_planes = ["tree_idx", "tree_val", "nonces"]
    if recursive:
        tree_planes.append("tree_leaf")
    fetch_acct = {}
    for pname in tree_planes:
        moved = rows[pname]
        gathers = [r for op, r in moved if op == "gather"]
        scatters = [(op, r) for op, r in moved if op != "gather"]
        assert not scatters, (
            f"shards={n_shards} E={window}: per-shard fetch round "
            f"SCATTERS to local {pname} ({scatters}) — the sharded "
            "steady-state round must be read-only on every chip's HBM"
        )
        assert gathers and all(r == want_fetch for r in gathers), (
            f"shards={n_shards} E={window}: per-shard {pname} fetch "
            f"gathers move {sorted(set(gathers))} rows — want the "
            f"uniform working-set shape B·(path_len−k) = {want_fetch} "
            "on every chip (non-owned lanes masked, never absent)"
        )
        fetch_acct[pname] = sorted(set(gathers))

    # -- 2. per-shard flush: t-row scatters, no local tree reads --------
    frows = _shared_plane_rows(
        _trace_sharded(cfg, "flush", mesh), lplanes
    )
    flush_acct = {}
    for pname in tree_planes:
        moved = frows[pname]
        gathers = [r for op, r in moved if op == "gather"]
        scatters = [r for op, r in moved if op != "gather"]
        assert not gathers, (
            f"shards={n_shards} E={window}: sharded flush GATHERS from "
            f"local {pname} — the window's live rows were already "
            "pulled at fetch time"
        )
        assert scatters and all(r == want_flush for r in scatters), (
            f"shards={n_shards} E={window}: per-shard {pname} flush "
            f"scatters move {sorted(set(scatters))} rows — want all "
            f"t = {want_flush} rows on every chip (the owner mask drops "
            "lanes via out-of-range targets; the static shape is the "
            "leak argument and never shrinks)"
        )
        flush_acct[pname] = sorted(set(scatters))

    if not runtime:
        out = {
            "fetch": fetch_acct, "flush": flush_acct,
            "want_fetch_rows": want_fetch, "want_flush_rows": want_flush,
            "shards": n_shards,
        }
        if verbose:
            print(f"sharded E={window} k={k} shards={n_shards} "
                  f"({'recursive' if recursive else 'flat'}, trace "
                  f"only): {out}")
        return out

    # -- 3. runtime owner partition (+ the seeded-mutant hook) ----------
    import functools

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from grapevine_tpu.oram import round as round_mod
    from grapevine_tpu.oram.path_oram import init_oram
    from grapevine_tpu.parallel.mesh import (
        _SHARD_MAP_NOCHECK, TREE_AXIS, _oram_specs, _shard_map,
    )

    def apply_batch(vals0, present0):
        return jnp.sum(vals0, axis=1), vals0, present0

    def run_round(axis, st, idxs, nl, dl, pm_nl, pm_dl):
        return round_mod.oram_round(
            cfg, st, idxs, nl, dl, apply_batch, axis_name=axis,
            pm_new_leaves=pm_nl if recursive else None,
            pm_dummy_leaves=pm_dl if recursive else None,
        )

    specs = _oram_specs()
    s_round = jax.jit(_shard_map(
        functools.partial(run_round, TREE_AXIS),
        mesh=mesh, in_specs=(specs, P(), P(), P(), P(), P()),
        out_specs=(specs, P(), P()), **_SHARD_MAP_NOCHECK,
    ))
    s_flush = jax.jit(_shard_map(
        lambda st: round_mod.oram_flush(cfg, st, TREE_AXIS),
        mesh=mesh, in_specs=(specs,), out_specs=specs,
        **_SHARD_MAP_NOCHECK,
    ))
    one_round = jax.jit(functools.partial(run_round, None))
    one_flush = jax.jit(lambda st: round_mod.oram_flush(cfg, st, None))

    rng = np.random.default_rng(5)
    st_s = st_1 = init_oram(cfg, jax.random.PRNGKey(7))
    for _ in range(window):
        idxs = rng.integers(0, cfg.blocks + 1, b).astype(np.uint32)
        draws = [rng.integers(0, cfg.leaves, b).astype(np.uint32)
                 for _ in range(4)]
        st_s, out_s, tr_s = s_round(st_s, idxs, *draws)
        st_1, out_1, tr_1 = one_round(st_1, idxs, *draws)
        np.testing.assert_array_equal(np.asarray(tr_s), np.asarray(tr_1))
    pre = jax.tree.map(np.asarray, st_1)
    orig_scatter = round_mod._path_scatter
    if _unmasked_scatter:
        round_mod._path_scatter = _unmasked_scatter_mutant(orig_scatter)
    try:
        post_s = jax.tree.map(np.asarray, s_flush(st_s))
    finally:
        round_mod._path_scatter = orig_scatter
    post_1 = jax.tree.map(np.asarray, one_flush(st_1))

    # every flush rewrites its targets' nonces, so changed nonce rows ≡
    # written buckets; the assembled sharded planes concatenate each
    # chip's local writes in heap order, so shard s's slice holds
    # exactly what shard s wrote
    def _written(post):
        return np.nonzero(
            (post.nonces != pre.nonces).any(axis=1)
        )[0]

    oracle_rows = set(_written(post_1).tolist())
    per_shard, union = [], set()
    for s in range(n_shards):
        lo, hi = s * n_local, (s + 1) * n_local
        ch = {
            int(r) + lo
            for r in np.nonzero(
                (post_s.nonces[lo:hi] != pre.nonces[lo:hi]).any(axis=1)
            )[0]
        }
        assert all(lo <= r < hi for r in ch)
        per_shard.append(len(ch))
        union |= ch
    assert sum(per_shard) == len(oracle_rows) and union == oracle_rows, (
        f"shards={n_shards} E={window}: owner partition violated — "
        f"per-shard written rows {per_shard} (sum {sum(per_shard)}) vs "
        f"the single-chip flush's {len(oracle_rows)} written rows; "
        "every written bucket must be written by exactly its heap-range "
        "owner and the union must be the single-chip write set"
    )
    for name in ("tree_idx", "tree_val", "nonces", "tree_leaf"):
        np.testing.assert_array_equal(
            getattr(post_s, name), getattr(post_1, name),
            err_msg=f"shards={n_shards} E={window}: sharded flush "
            f"diverges from single-chip on {name}",
        )

    out = {
        "fetch": fetch_acct, "flush": flush_acct,
        "want_fetch_rows": want_fetch, "want_flush_rows": want_flush,
        "per_shard_written": per_shard,
        "oracle_written": len(oracle_rows),
        "shards": n_shards,
    }
    if verbose:
        print(f"sharded E={window} k={k} shards={n_shards} "
              f"({'recursive' if recursive else 'flat'}): {out}")
    return out


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--height", type=int, default=5)
    args = ap.parse_args(argv)
    if "jax" not in sys.modules:
        # the sharded audit needs a real (if simulated) multi-device
        # mesh; standalone runs get one before the backend initializes
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=2"
            ).strip()
    for recursive in (False, True):
        out = check_tree_cache_schedule(
            b=args.batch, height=args.height, verbose=True,
            recursive=recursive,
        )
        print(f"[check_tree_cache_oblivious] recursive={recursive}: OK {out}")
    out = check_k0_recursive_census(b=4, height=5)
    print(f"[check_tree_cache_oblivious] k0-recursive cell: OK {out}")
    for recursive in (False, True):
        out = check_evict_round_accounting(verbose=True,
                                           recursive=recursive)
        print(f"[check_tree_cache_oblivious] evict schedule "
              f"(recursive={recursive}): OK")
    for recursive in (False, True):
        out = check_sharded_evict_accounting(verbose=True,
                                             recursive=recursive)
        print(f"[check_tree_cache_oblivious] sharded evict schedule "
              f"(recursive={recursive}): OK")
    try:
        check_sharded_evict_accounting(_unmasked_scatter=True)
    except AssertionError as exc:
        print("[check_tree_cache_oblivious] seeded unmasked-scatter "
              f"mutant: CAUGHT ({str(exc)[:72]}...)")
    else:
        print("[check_tree_cache_oblivious] FAIL: seeded unmasked-"
              "scatter mutant passed the sharded partition audit")
        return 1
    print("[check_tree_cache_oblivious] PASS: cached round is index-blind "
          "and HBM path traffic is exactly B·(path_len−k) rows per plane; "
          "delayed-eviction fetch rounds are HBM-read-only, each flush "
          "writes exactly the E-round window, and the sharded flush "
          "owner-partitions that window across the mesh")
    return 0


if __name__ == "__main__":
    sys.exit(main())
