#!/usr/bin/env python
"""CI gate: geometry-scale overflow certification of the compiled round.

Rangelint (grapevine_tpu/analysis/rangelint.py) abstract-interprets the
closed jaxpr of the full engine round, the expiry sweep, and the
standalone library sub-rounds (oram_round, lookup_remap_round) with a
per-dtype interval domain: geometry-derived input ranges are declared at
the RANGELINT_BOUNDS anchors (oram/path_oram.py, oram/posmap.py,
engine/round_step.py, engine/expiry.py; engine/journal.py holds the
host-side byte-length guard) and propagated through every primitive with
a scan/while carry fixpoint, flagging u32/int32 wraparound, truncating
casts, and gather/slice indices that can leave their axis (XLA clamps
would hide those). Intentional mod-2^32 sites (ChaCha ARX, the keyed
mixers, u64 two-lane carries) pass through the reviewed RANGE_ALLOWLIST,
each entry with its one-line range argument; dead entries fail the run.

Sweep: the shipped knob combinations over {vphases_impl, sort_impl,
posmap_impl, tree_top_cache_levels, evict_every} at the declared
``--geometry`` (log2 records; default 30 — the max certified per-tree
capacity, where every allowlist entry genuinely fires), engine round +
expiry sweep + standalone oram_round/lookup_remap_round per combo, plus
the standalone flush programs (engine_flush_step / oram_flush — the
write half of the delayed round) on every E > 1 combo. ``--full``
sweeps the 2x2x2x2x2 cross-product (the -m slow tier). ``--smoke`` is the tier-1 budget: one
combo at toy geometry, traces only, zero engine compiles.

Geometry certification: ``--geometry 30`` certifies today's capacity
point clean; ``--geometry 36`` (the ROADMAP item 4 design point) must be
*refused* by the construction-time guard (oram/path_oram.py
OramConfig.__post_init__ — the certified u32 bound is height <= 29 /
blocks <= 2^30), and this report cites that refusal plus the certified
composition: 2^36 records = 2^6 recipient-space shards x 2^30 (ROADMAP
item 2), each shard's compiled round certified clean here — or a deeper
recursion with widened lanes (item 4). A beyond-bound geometry that
constructs WITHOUT refusing fails this gate.

Teeth: the seeded overflow mutants (grapevine_tpu/analysis/mutants.py
_RANGE_REGISTRY — u32 leaf-arith wrap, truncating cast, off-by-one axis
bound, unbounded scan counter, eviction-buffer index overflow, int32
byte-size product) run under the
production range allowlist on every invocation and must each FAIL.

Standalone: ``python tools/check_ranges.py [--smoke|--full]
[--geometry N]``; tier-1: tests/test_rangelint.py (next to the
telemetry/seal/oblint gates).
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

#: shipped auto-reachable knob combinations — the check_oblivious set,
#: so the two analyzers certify the identical program matrix
DEFAULT_COMBOS = (
    ("dense", "xla", "flat", 0, 1),
    ("scan", "xla", "recursive", 2, 2),
    ("scan", "radix", "flat", 2, 4),
    ("dense", "radix", "recursive", 0, 2),
)
#: tier-1 budget: ONE combo — pinned at E=2 (matching check_oblivious's
#: smoke) so the delayed-eviction fetch round and its buffer-index
#: arithmetic always have an always-on interval census
SMOKE_COMBO = ("dense", "xla", "flat", 0, 2)

#: default certification geometry (log2 records) for the standalone
#: sweep: the max certified per-tree capacity — several allowlist
#: entries (e.g. the _rank_pass rank recombination) only *fire* once
#: the lanes get tight, so reachability at toy geometry would misread
#: them as dead. --smoke uses the toy engine regardless.
DEFAULT_GEOMETRY = 30

#: the ROADMAP item 4 design point: must be REFUSED at construction
DESIGN_POINT = 36

#: the largest per-tree records capacity the u32 lanes certify (density
#: 2: height 29 payload trees) — the shard size of the 2^36 composition
MAX_CERTIFIED_GEOMETRY = 30


def _engine(log2_msgs: int, vp: str, srt: str, pmi: str, k: int,
            ee: int = 1, batch: int = 4):
    from grapevine_tpu.config import GrapevineConfig
    from grapevine_tpu.engine.state import EngineConfig

    cfg = GrapevineConfig(
        max_messages=1 << log2_msgs,
        max_recipients=max(16, 1 << min(log2_msgs, 20)),
        batch_size=batch,
        vphases_impl=vp, sort_impl=srt, posmap_impl=pmi,
        tree_top_cache_levels=k, evict_every=ee,
    )
    return EngineConfig.from_config(cfg)


def _batch_spec(ecfg):
    import jax
    import numpy as np

    from grapevine_tpu.engine.state import (
        ID_WORDS, KEY_WORDS, PAYLOAD_WORDS,
    )

    b = ecfg.batch_size

    def s(*sh):
        return jax.ShapeDtypeStruct(sh, np.uint32)

    return {
        "req_type": s(b), "auth": s(b, KEY_WORDS),
        "msg_id": s(b, ID_WORDS), "recipient": s(b, KEY_WORDS),
        "payload": s(b, PAYLOAD_WORDS), "now": s(), "now_hi": s(),
    }


def audit_engine_round(ecfg, allowlist, name: str):
    """Interval-audit one full engine round (trace only, no compile)."""
    import jax

    from grapevine_tpu.analysis.rangelint import analyze_ranges
    from grapevine_tpu.engine import round_step
    from grapevine_tpu.engine.state import init_engine

    state = jax.eval_shape(lambda: init_engine(ecfg, 0))
    return analyze_ranges(
        lambda st, ba: round_step.engine_round_step(ecfg, st, ba),
        {"state": state, "batch": _batch_spec(ecfg)},
        bounds=round_step.RANGELINT_BOUNDS(ecfg),
        allowlist=allowlist,
        name=f"engine_round/{name}",
    )


def audit_engine_flush(ecfg, allowlist, name: str):
    """Interval-audit the standalone delayed-eviction flush program —
    the write half of the E-round schedule (engine_flush_step; E > 1
    only). Its inputs are the state planes alone (the flush consumes no
    batch), so the bounds are the round's state.* anchors — the same
    dict, batch keys simply unmatched-by-construction."""
    import jax

    from grapevine_tpu.analysis.rangelint import analyze_ranges
    from grapevine_tpu.engine import round_step
    from grapevine_tpu.engine.state import init_engine

    state = jax.eval_shape(lambda: init_engine(ecfg, 0))
    return analyze_ranges(
        lambda st: round_step.engine_flush_step(ecfg, st),
        {"state": state},
        bounds=round_step.RANGELINT_BOUNDS(ecfg),
        allowlist=allowlist,
        name=f"engine_flush/{name}",
    )


def audit_expiry_sweep(ecfg, allowlist, name: str):
    import jax
    import numpy as np

    from grapevine_tpu.analysis.rangelint import analyze_ranges
    from grapevine_tpu.engine import expiry
    from grapevine_tpu.engine.state import init_engine

    state = jax.eval_shape(lambda: init_engine(ecfg, 0))
    scalar = jax.ShapeDtypeStruct((), np.uint32)
    return analyze_ranges(
        lambda st, now, per, nh: expiry.expiry_sweep(ecfg, st, now, per, nh),
        {"state": state, "now": scalar, "period": scalar, "now_hi": scalar},
        bounds=expiry.RANGELINT_BOUNDS(ecfg),
        allowlist=allowlist,
        name=f"expiry_sweep/{name}",
    )


def _oram_cfg(log2_blocks: int, recursive: bool, k: int, ee: int = 1,
              b: int = 4):
    from grapevine_tpu.oram.path_oram import OramConfig
    from grapevine_tpu.oram.posmap import derive_posmap_spec

    blocks = 1 << log2_blocks
    pm = (
        derive_posmap_spec(blocks, top_cache_levels=k,
                           evict_window=ee, evict_fetch_count=b)
        if recursive
        else None
    )
    return OramConfig(
        height=max(1, log2_blocks - 1), value_words=4, n_blocks=blocks,
        cipher_rounds=8, posmap=pm, top_cache_levels=k,
        evict_window=ee, evict_fetch_count=b if ee > 1 else 0,
        evict_buffer_slots=min(blocks, 64) if ee > 1 else 0,
    )


def audit_oram_flush(allowlist, log2_blocks: int, sort_impl: str,
                     recursive: bool, k: int, ee: int):
    """Interval-audit oram_flush standalone (the library write half of
    the delayed round) against the tree's state-plane anchors."""
    import jax

    from grapevine_tpu.analysis.rangelint import analyze_ranges
    from grapevine_tpu.oram import posmap as pmod
    from grapevine_tpu.oram import round as oround
    from grapevine_tpu.oram.path_oram import (
        RANGELINT_BOUNDS as tree_bounds, init_oram,
    )

    cfg = _oram_cfg(log2_blocks, recursive, k, ee=ee)
    state = jax.eval_shape(lambda: init_oram(cfg, jax.random.PRNGKey(0)))
    bounds = {
        **tree_bounds(cfg, prefix="state"),
        **pmod.RANGELINT_BOUNDS(cfg, prefix="state.posmap"),
    }
    bounds = {k2: v for k2, v in bounds.items()
              if not k2.startswith("pm_state")}
    return analyze_ranges(
        lambda state: oround.oram_flush(cfg, state, sort_impl=sort_impl),
        {"state": state},
        bounds=bounds,
        allowlist=allowlist,
        name=f"oram_flush/2^{log2_blocks}_{sort_impl}_"
             f"{'rec' if recursive else 'flat'}_k{k}_e{ee}",
    )


def audit_sharded_oram_flush(allowlist, log2_blocks: int, sort_impl: str,
                             recursive: bool, k: int, ee: int,
                             shards: int):
    """Interval-audit the owner-masked sharded flush (ISSUE 18): the
    same ``oram_flush`` program wrapped in ``shard_map`` over a
    ``shards``-device bucket-axis mesh. New arithmetic vs the
    single-chip flush: ``axis_index`` (bounded [0, shards-1] by the
    rangelint mesh rule) and the per-chip rebase in ``_path_scatter``
    — non-owned lanes wrap mod 2^32 by construction and land on the
    drop sentinel, a reviewed RANGE_ALLOWLIST pair. Trace-only, like
    every audit here."""
    import jax

    from grapevine_tpu.analysis.rangelint import analyze_ranges
    from grapevine_tpu.oram import posmap as pmod
    from grapevine_tpu.oram import round as oround
    from grapevine_tpu.oram.path_oram import (
        RANGELINT_BOUNDS as tree_bounds, init_oram,
    )
    from grapevine_tpu.parallel.mesh import (
        _SHARD_MAP_NOCHECK, TREE_AXIS, _oram_specs, _shard_map,
        make_mesh,
    )

    cfg = _oram_cfg(log2_blocks, recursive, k, ee=ee)
    state = jax.eval_shape(lambda: init_oram(cfg, jax.random.PRNGKey(0)))
    mesh = make_mesh(jax.devices()[:shards])
    specs = _oram_specs()
    fn = _shard_map(
        lambda st: oround.oram_flush(cfg, st, TREE_AXIS,
                                     sort_impl=sort_impl),
        mesh=mesh, in_specs=(specs,), out_specs=specs,
        **_SHARD_MAP_NOCHECK,
    )
    bounds = {
        **tree_bounds(cfg, prefix="state"),
        **pmod.RANGELINT_BOUNDS(cfg, prefix="state.posmap"),
    }
    bounds = {k2: v for k2, v in bounds.items()
              if not k2.startswith("pm_state")}
    return analyze_ranges(
        fn,
        {"state": state},
        bounds=bounds,
        allowlist=allowlist,
        name=f"sharded_oram_flush/2^{log2_blocks}_{sort_impl}_"
             f"{'rec' if recursive else 'flat'}_k{k}_e{ee}_s{shards}",
    )


def audit_oram_round(allowlist, log2_blocks: int, occ_impl: str,
                     sort_impl: str, recursive: bool, k: int,
                     ee: int = 1):
    import jax
    import jax.numpy as jnp

    from grapevine_tpu.analysis.rangelint import analyze_ranges
    from grapevine_tpu.oram import posmap as pmod
    from grapevine_tpu.oram import round as oround
    from grapevine_tpu.oram.path_oram import (
        RANGELINT_BOUNDS as tree_bounds, init_oram,
    )

    cfg = _oram_cfg(log2_blocks, recursive, k, ee=ee)
    state = jax.eval_shape(lambda: init_oram(cfg, jax.random.PRNGKey(0)))
    b = 4

    def sds(*sh):
        return jax.ShapeDtypeStruct(sh, jnp.uint32)

    def apply_batch(vals0, present0):
        # pass-through callback: the audit certifies the round machinery
        return vals0[:, 0], vals0, present0

    def run(state, idxs, new_leaves, dummy_leaves, pm_new_leaves,
            pm_dummy_leaves):
        return oround.oram_round(
            cfg, state, idxs, new_leaves, dummy_leaves, apply_batch,
            occ_impl=occ_impl, sort_impl=sort_impl,
            pm_new_leaves=pm_new_leaves if recursive else None,
            pm_dummy_leaves=pm_dummy_leaves if recursive else None,
        )

    bounds = {
        **tree_bounds(cfg, prefix="state"),
        **pmod.RANGELINT_BOUNDS(cfg, prefix="state.posmap"),
    }
    # the posmap anchor's pm_state.* labels do not apply here (the map
    # rides inside state.posmap, covered by the tree anchor)
    bounds = {k2: v for k2, v in bounds.items()
              if not k2.startswith("pm_state")}
    return analyze_ranges(
        run,
        {"state": state, "idxs": sds(b), "new_leaves": sds(b),
         "dummy_leaves": sds(b), "pm_new_leaves": sds(b),
         "pm_dummy_leaves": sds(b)},
        bounds=bounds,
        allowlist=allowlist,
        name=f"oram_round/2^{log2_blocks}_{occ_impl}_{sort_impl}_"
             f"{'rec' if recursive else 'flat'}_k{k}_e{ee}",
    )


def audit_lookup_remap(allowlist, log2_blocks: int, occ_impl: str,
                       sort_impl: str, recursive: bool):
    import jax
    import jax.numpy as jnp

    from grapevine_tpu.analysis.rangelint import analyze_ranges
    from grapevine_tpu.oram import posmap as pmod
    from grapevine_tpu.oram.posmap import init_posmap

    cfg = _oram_cfg(log2_blocks, recursive, 0)
    pm_state = jax.eval_shape(
        lambda: init_posmap(cfg, jax.random.PRNGKey(0))
    )
    b = 4

    def sds(*sh, dt=jnp.uint32):
        return jax.ShapeDtypeStruct(sh, dt)

    def run(pm_state, idxs, new_leaves, dummy_leaves, first_occ,
            last_occ, pm_new_leaves, pm_dummy_leaves):
        return pmod.lookup_remap_round(
            cfg, pm_state, idxs, new_leaves, dummy_leaves,
            first_occ, last_occ,
            pm_new_leaves=pm_new_leaves if recursive else None,
            pm_dummy_leaves=pm_dummy_leaves if recursive else None,
            occ_impl=occ_impl, sort_impl=sort_impl,
        )

    return analyze_ranges(
        run,
        {"pm_state": pm_state, "idxs": sds(b), "new_leaves": sds(b),
         "dummy_leaves": sds(b), "first_occ": sds(b, dt=jnp.bool_),
         "last_occ": sds(b, dt=jnp.bool_), "pm_new_leaves": sds(b),
         "pm_dummy_leaves": sds(b)},
        bounds=pmod.RANGELINT_BOUNDS(cfg),
        allowlist=allowlist,
        name=f"lookup_remap/2^{log2_blocks}_{occ_impl}_{sort_impl}_"
             f"{'rec' if recursive else 'flat'}",
    )


def run_range_mutant_controls(allowlist) -> list:
    """Every seeded overflow mutant must FAIL under the production
    range allowlist (the shared control reporter both drivers use)."""
    from grapevine_tpu.analysis.mutants import (
        control_failures, run_range_mutants,
    )

    log = lambda line: print(f"[check_ranges] {line}")  # noqa: E731
    return control_failures(
        run_range_mutants(allowlist), "range mutant", log
    )


def run_audit(combos, geometry: int, allowlist=None, verbose=False,
              with_subrounds: bool = True):
    """Sweep the interval audit; returns (problems, allowlist_hits)."""
    from grapevine_tpu.analysis.allowlist import RANGE_ALLOWLIST

    if allowlist is None:
        allowlist = RANGE_ALLOWLIST
    problems: list = []
    hits: dict = {}

    def absorb(rep):
        for k2, n in rep.allowed.items():
            hits[k2] = hits.get(k2, 0) + n
        if verbose or rep.findings:
            print(rep.summary())
        problems.extend(f"{rep.name}: {f}" for f in rep.findings)

    # engine geometry: max_messages = 2^geometry; sub-round geometry:
    # the same block count standalone
    for vp, srt, pmi, k, ee in combos:
        name = f"2^{geometry}_{vp}_{srt}_{pmi}_k{k}_e{ee}"
        ecfg = _engine(geometry, vp, srt, pmi, k, ee)
        absorb(audit_engine_round(ecfg, allowlist, name))
        absorb(audit_expiry_sweep(ecfg, allowlist, name))
        if ee > 1:
            # the write half of the delayed round: the flush program
            # audits standalone (it runs as its own dispatch)
            absorb(audit_engine_flush(ecfg, allowlist, name))
        if with_subrounds:
            absorb(audit_oram_round(
                allowlist, geometry, occ_impl=vp, sort_impl=srt,
                recursive=(pmi == "recursive"), k=k, ee=ee,
            ))
            absorb(audit_lookup_remap(
                allowlist, geometry, occ_impl=vp, sort_impl=srt,
                recursive=(pmi == "recursive"),
            ))
            if ee > 1:
                absorb(audit_oram_flush(
                    allowlist, geometry, sort_impl=srt,
                    recursive=(pmi == "recursive"), k=k, ee=ee,
                ))
                import jax

                if len(jax.devices()) >= 2:
                    # the mesh composition of the same flush (ISSUE
                    # 18): 2 shards is where every sharded-only lane
                    # (axis_index, the _path_scatter rebase) exists
                    absorb(audit_sharded_oram_flush(
                        allowlist, geometry, sort_impl=srt,
                        recursive=(pmi == "recursive"), k=k, ee=ee,
                        shards=2,
                    ))
                else:  # pragma: no cover - bootstrap in main()
                    problems.append(
                        "sharded flush audit needs >= 2 devices (got "
                        "1) — run standalone (main() forces a virtual "
                        "2-device CPU mesh) or under the test "
                        "harness's 8-device conftest"
                    )
    return problems, hits


def check_allowlist_reachability(hits: dict) -> list:
    """Every reviewed range entry must fire somewhere in the sweep."""
    from grapevine_tpu.analysis.allowlist import RANGE_ALLOWLIST

    dead = [e for e in RANGE_ALLOWLIST if e.key not in hits]
    return [
        f"dead range-allowlist entry {e.key!r} ({e.reason!r}): never "
        "reached in any swept knob combination — delete it or sweep the "
        "combo that exercises it (dead entries rot into blanket "
        "permissions)"
        for e in dead
    ]


def certify_design_point(log2_records: int) -> "tuple[list, str]":
    """A beyond-bound geometry must REFUSE at construction, citing the
    certified bound; returns (problems, the refusal text this report
    cites)."""
    try:
        _engine(log2_records, "dense", "xla", "flat", 0)
    except ValueError as exc:
        return [], str(exc)
    return [
        f"2^{log2_records} records constructed WITHOUT a certified-"
        "geometry refusal — the u32 lanes are not certified there; the "
        "construction guard (oram/path_oram.py OramConfig) must refuse "
        "beyond the certified bound"
    ], ""


def main(argv=None) -> int:
    import argparse

    # the sharded flush audit traces a 2-device shard_map: force a
    # virtual CPU mesh if jax has not initialized yet (standalone
    # invocation; in-process the test conftest already forces 8)
    if ("jax" not in sys.modules
            and "xla_force_host_platform_device_count"
            not in os.environ.get("XLA_FLAGS", "")):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=2"
        ).strip()

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tier-1 budget: one toy-geometry combo, engine "
                         "trace + range mutants + design-point refusal; "
                         "zero compiles")
    ap.add_argument("--full", action="store_true",
                    help="full 2x2x2x2x2 knob cross-product (the -m slow "
                         "tier)")
    ap.add_argument("--geometry", type=int, default=None, metavar="LOG2",
                    help=f"records capacity to certify (log2; default "
                         f"{DEFAULT_GEOMETRY}; {DESIGN_POINT} = the "
                         "design point, certified via refusal + the "
                         "max certified shard geometry)")
    ap.add_argument("--skip-mutants", action="store_true")
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)

    from grapevine_tpu.analysis.allowlist import RANGE_ALLOWLIST

    problems: list = []
    geometry = args.geometry if args.geometry is not None else (
        DEFAULT_GEOMETRY
    )

    if args.smoke:
        vp, srt, pmi, k, ee = SMOKE_COMBO
        ecfg = _engine(5, vp, srt, pmi, k, ee)
        rep = audit_engine_round(
            ecfg, RANGE_ALLOWLIST, f"smoke_{vp}_{srt}_{pmi}_k{k}_e{ee}",
        )
        print(rep.summary())
        problems.extend(f"{rep.name}: {f}" for f in rep.findings)
        rep = audit_engine_flush(
            ecfg, RANGE_ALLOWLIST, f"smoke_{vp}_{srt}_{pmi}_k{k}_e{ee}",
        )
        print(rep.summary())
        problems.extend(f"{rep.name}: {f}" for f in rep.findings)
        import jax

        if len(jax.devices()) >= 2:
            # always-on sharded lane coverage (trace-only): the
            # owner-masked flush's rebase arithmetic at toy geometry
            rep = audit_sharded_oram_flush(
                RANGE_ALLOWLIST, 5, sort_impl=srt,
                recursive=(pmi == "recursive"), k=k, ee=ee, shards=2,
            )
            print(rep.summary())
            problems.extend(f"{rep.name}: {f}" for f in rep.findings)
        dp, refusal = certify_design_point(DESIGN_POINT)
        problems.extend(dp)
        if refusal:
            print(f"[check_ranges] 2^{DESIGN_POINT} design point: "
                  f"REFUSED at construction (certified) — {refusal}")
    else:
        sweep_geometry = geometry
        refusal = ""
        if geometry > MAX_CERTIFIED_GEOMETRY:
            dp, refusal = certify_design_point(geometry)
            problems.extend(dp)
            if refusal:
                print(
                    f"[check_ranges] 2^{geometry} records: REFUSED at "
                    f"construction (certified) — {refusal}\n"
                    f"[check_ranges] certifying the composition shard "
                    f"instead: 2^{geometry} = "
                    f"2^{geometry - MAX_CERTIFIED_GEOMETRY} recipient-"
                    f"space shards x 2^{MAX_CERTIFIED_GEOMETRY} records "
                    "(ROADMAP item 2), or a deeper recursion with "
                    "widened lanes (item 4)"
                )
            sweep_geometry = MAX_CERTIFIED_GEOMETRY
        combos = None
        if args.full:
            import itertools

            combos = tuple(itertools.product(
                ("dense", "scan"), ("xla", "radix"),
                ("flat", "recursive"), (0, 2), (1, 2),
            ))
        swept, hits = run_audit(
            combos or DEFAULT_COMBOS, sweep_geometry,
            verbose=args.verbose,
        )
        problems.extend(swept)
        problems.extend(check_allowlist_reachability(hits))

    if not args.skip_mutants:
        problems.extend(run_range_mutant_controls(RANGE_ALLOWLIST))

    if problems:
        print(f"[check_ranges] FAIL: {len(problems)} problem(s)")
        for p in problems:
            print(f"  - {p}")
        return 1
    scope = (
        "smoke combo" if args.smoke
        else f"full knob matrix @ 2^{geometry}" if args.full
        else f"shipped knob matrix @ 2^{geometry}"
    )
    reach = "" if args.smoke else "; every range-allowlist entry reachable"
    teeth = "" if args.skip_mutants else "; all overflow mutants caught"
    print(f"[check_ranges] PASS ({scope}): no wraparound, truncating "
          f"cast, or clamped-OOB index outside the reviewed mod-2^32 "
          f"allowlist{reach}{teeth}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
