#!/usr/bin/env python
"""CI gate: unified taint-based obliviousness audit of the engine round
(+ the host-path lock-discipline lint).

One analyzer (grapevine_tpu/analysis/oblint.py) replaces the per-feature
checkers' scattered proofs: secret engine inputs (recipient keys, msg
ids, positions, stash/cache contents, cipher keys, payloads — declared
as OBLINT_SECRETS anchors next to the code where each secret enters) are
tainted at trace time, and the closed jaxpr of the full engine round,
the expiry sweep, and the library sub-rounds (oram_round,
lookup_remap_round) is walked proving no gather/scatter index, no
cond/while predicate, no dynamic-slice start, and no host callback is
secret-derived — modulo the reviewed allowlist
(grapevine_tpu/analysis/allowlist.py), every entry of which carries its
one-line leak argument AND must be *reached* somewhere in the swept knob
matrix (dead entries fail the run).

Sweep: the shipped knob combinations over
{vphases_impl, sort_impl, posmap_impl, tree_top_cache_levels} by
default; the full 2x2x2x2 cross-product under ``--full`` (the -m slow
tier). ``--smoke`` is the tier-1 budget: one representative combo, one
engine trace, no compile.

Teeth: the seeded mutants (grapevine_tpu/analysis/mutants.py) run under
the production allowlists on every invocation and must each FAIL — the
seven leak classes (position-dependent branch, key-indexed gather,
data-dependent early exit, secret-shaped output, un-allowlisted
scatter, leaky debug print, python-level branch) AND, since ISSUE 14,
the six overflow classes through the rangelint sibling analyzer (one
shared runner proves both analyzers alive from this one tier-1 gate;
tools/check_ranges.py is the overflow analyzer's own driver). A
passing mutant fails this gate.

The host prong: grapevine_tpu/analysis/locklint.py statically asserts
the PR-10 pipeline discipline (journal+dispatch in exactly one engine
lock hold, stage-1 outside every lock, lock-free journal, acyclic lock
ordering, role-covered shared attributes).

Standalone: ``python tools/check_oblivious.py [--smoke|--full]``;
tier-1: tests/test_oblint.py (next to the telemetry/seal/perf gates).
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

#: shipped auto-reachable knob combinations (vphases, sort, posmap, k,
#: evict_every): chosen so every allowlist entry is reachable —
#: dense+scan, xla+radix, flat+recursive, cached+uncached, and
#: per-round vs delayed eviction all appear, in the pairings the `auto`
#: resolution ships (config.py: dense/xla is the measured CPU default;
#: scan/radix the TPU-leaning pairing; recursive and delayed eviction
#: ride both). E > 1 combos additionally audit the standalone flush
#: program (engine_flush_step / oram_flush) — the write half of the
#: delayed round.
DEFAULT_COMBOS = (
    ("dense", "xla", "flat", 0, 1),
    ("scan", "xla", "recursive", 2, 2),
    ("scan", "radix", "flat", 2, 4),
    ("dense", "radix", "recursive", 0, 2),
)
#: tier-1 budget: ONE combo — pinned at E=2 so the fetch-only round
#: (the steady-state program a delayed-eviction server runs) always has
#: an always-on taint census
SMOKE_COMBO = ("dense", "xla", "flat", 0, 2)


def _small_engine(vp: str, srt: str, pmi: str, k: int, ee: int = 1):
    from grapevine_tpu.config import GrapevineConfig
    from grapevine_tpu.engine.state import EngineConfig

    cfg = GrapevineConfig(
        max_messages=32, max_recipients=16, batch_size=4,
        vphases_impl=vp, sort_impl=srt, posmap_impl=pmi,
        tree_top_cache_levels=k, evict_every=ee,
    )
    return EngineConfig.from_config(cfg)


def _batch_spec(ecfg):
    import jax
    import numpy as np

    from grapevine_tpu.engine.state import (
        ID_WORDS, KEY_WORDS, PAYLOAD_WORDS,
    )

    b = ecfg.batch_size

    def s(*sh):
        return jax.ShapeDtypeStruct(sh, np.uint32)

    return {
        "req_type": s(b), "auth": s(b, KEY_WORDS),
        "msg_id": s(b, ID_WORDS), "recipient": s(b, KEY_WORDS),
        "payload": s(b, PAYLOAD_WORDS), "now": s(), "now_hi": s(),
    }


def audit_engine_round(ecfg, allowlist, name: str):
    """Taint-audit one full engine round (trace only, no compile)."""
    import jax

    from grapevine_tpu.analysis.oblint import analyze
    from grapevine_tpu.engine import round_step
    from grapevine_tpu.engine.state import init_engine

    state = jax.eval_shape(lambda: init_engine(ecfg, 0))
    return analyze(
        lambda st, ba: round_step.engine_round_step(ecfg, st, ba),
        {"state": state, "batch": _batch_spec(ecfg)},
        secrets=round_step.OBLINT_SECRETS,
        allowlist=allowlist,
        name=f"engine_round/{name}",
    )


def audit_expiry_sweep(ecfg, allowlist, name: str):
    import jax
    import numpy as np

    from grapevine_tpu.analysis.oblint import analyze
    from grapevine_tpu.engine import expiry
    from grapevine_tpu.engine.state import init_engine

    state = jax.eval_shape(lambda: init_engine(ecfg, 0))
    scalar = jax.ShapeDtypeStruct((), np.uint32)
    return analyze(
        lambda st, now, per, nh: expiry.expiry_sweep(ecfg, st, now, per, nh),
        {"state": state, "now": scalar, "period": scalar, "now_hi": scalar},
        secrets=expiry.OBLINT_SECRETS,
        allowlist=allowlist,
        name=f"expiry_sweep/{name}",
    )


def audit_engine_flush(ecfg, allowlist, name: str):
    """Taint-audit the standalone delayed-eviction flush program — the
    write half of the E-round schedule (engine_flush_step; E > 1
    engines only). Its bucket targets must derive ONLY from the
    untainted public window ledger."""
    import jax

    from grapevine_tpu.analysis.oblint import analyze
    from grapevine_tpu.engine import round_step
    from grapevine_tpu.engine.state import init_engine

    state = jax.eval_shape(lambda: init_engine(ecfg, 0))
    return analyze(
        lambda st: round_step.engine_flush_step(ecfg, st),
        {"state": state},
        secrets=round_step.FLUSH_OBLINT_SECRETS,
        allowlist=allowlist,
        name=f"engine_flush/{name}",
    )


def _small_oram_cfg(recursive: bool, k: int, ee: int = 1, b: int = 4):
    from grapevine_tpu.oram.path_oram import OramConfig
    from grapevine_tpu.oram.posmap import derive_posmap_spec

    pm = (
        derive_posmap_spec(16, top_cache_levels=k,
                           evict_window=ee, evict_fetch_count=b)
        if recursive
        else None
    )
    return OramConfig(
        height=4, value_words=4, n_blocks=16, cipher_rounds=8,
        posmap=pm, top_cache_levels=k,
        evict_window=ee, evict_fetch_count=b if ee > 1 else 0,
        evict_buffer_slots=16 if ee > 1 else 0,
    )


def audit_oram_flush(allowlist, sort_impl: str, recursive: bool, k: int,
                     ee: int = 2):
    """Taint-audit oram_flush standalone against the round's anchors
    (state-plane secrets only — flush takes no batch)."""
    import jax

    from grapevine_tpu.analysis.oblint import analyze
    from grapevine_tpu.oram import round as oround
    from grapevine_tpu.oram.path_oram import init_oram

    cfg = _small_oram_cfg(recursive, k, ee=ee)
    state = jax.eval_shape(lambda: init_oram(cfg, jax.random.PRNGKey(0)))
    return analyze(
        lambda state: oround.oram_flush(cfg, state, sort_impl=sort_impl),
        {"state": state},
        secrets=oround.OBLINT_SECRETS,
        allowlist=allowlist,
        name=f"oram_flush/{sort_impl}_"
             f"{'rec' if recursive else 'flat'}_k{k}_e{ee}",
    )


def audit_sharded_oram_flush(allowlist, sort_impl: str, recursive: bool,
                             k: int, ee: int = 2, shards: int = 2):
    """Taint-audit the owner-masked sharded flush (ISSUE 18): the same
    ``oram_flush`` wrapped in ``shard_map`` over a bucket-axis mesh.
    The certified claim extends per chip: every chip's scatter targets
    derive ONLY from the untainted public window ledger plus its own
    (public) mesh coordinate — the owner mask narrows which rows LAND,
    never which rows are DISPATCHED, so the per-chip transcript stays
    the uniform static-shape drop-mode scatter (the leak argument in
    parallel/mesh.py make_sharded_flush)."""
    import jax

    from grapevine_tpu.analysis.oblint import analyze
    from grapevine_tpu.oram import round as oround
    from grapevine_tpu.oram.path_oram import init_oram
    from grapevine_tpu.parallel.mesh import (
        _SHARD_MAP_NOCHECK, TREE_AXIS, _oram_specs, _shard_map,
        make_mesh,
    )

    cfg = _small_oram_cfg(recursive, k, ee=ee)
    state = jax.eval_shape(lambda: init_oram(cfg, jax.random.PRNGKey(0)))
    mesh = make_mesh(jax.devices()[:shards])
    specs = _oram_specs()
    fn = _shard_map(
        lambda st: oround.oram_flush(cfg, st, TREE_AXIS,
                                     sort_impl=sort_impl),
        mesh=mesh, in_specs=(specs,), out_specs=specs,
        **_SHARD_MAP_NOCHECK,
    )
    return analyze(
        fn,
        {"state": state},
        secrets=oround.OBLINT_SECRETS,
        allowlist=allowlist,
        name=f"sharded_oram_flush/{sort_impl}_"
             f"{'rec' if recursive else 'flat'}_k{k}_e{ee}_s{shards}",
    )


def audit_oram_round(allowlist, occ_impl: str, sort_impl: str,
                     recursive: bool, k: int, ee: int = 1):
    """Taint-audit the library sub-rounds standalone: oram_round (and
    through it lookup_remap_round) at a small geometry; ``ee > 1``
    traces the delayed-eviction fetch-only round instead."""
    import jax
    import jax.numpy as jnp

    from grapevine_tpu.analysis.oblint import analyze
    from grapevine_tpu.oram import round as oround
    from grapevine_tpu.oram.path_oram import init_oram

    cfg = _small_oram_cfg(recursive, k, ee=ee)
    state = jax.eval_shape(lambda: init_oram(cfg, jax.random.PRNGKey(0)))
    b = 4

    def sds(*sh):
        return jax.ShapeDtypeStruct(sh, jnp.uint32)

    def apply_batch(vals0, present0):
        return jnp.sum(vals0, axis=1), vals0, present0

    def run(state, idxs, new_leaves, dummy_leaves, pm_new_leaves,
            pm_dummy_leaves):
        return oround.oram_round(
            cfg, state, idxs, new_leaves, dummy_leaves, apply_batch,
            occ_impl=occ_impl, sort_impl=sort_impl,
            pm_new_leaves=pm_new_leaves if recursive else None,
            pm_dummy_leaves=pm_dummy_leaves if recursive else None,
        )

    return analyze(
        run,
        {"state": state, "idxs": sds(b), "new_leaves": sds(b),
         "dummy_leaves": sds(b), "pm_new_leaves": sds(b),
         "pm_dummy_leaves": sds(b)},
        secrets=oround.OBLINT_SECRETS,
        allowlist=allowlist,
        name=f"oram_round/{occ_impl}_{sort_impl}_"
             f"{'rec' if recursive else 'flat'}_k{k}_e{ee}",
    )


def audit_lookup_remap(allowlist, occ_impl: str, sort_impl: str,
                       recursive: bool):
    """Taint-audit lookup_remap_round standalone against ITS OWN
    anchors (oram/posmap.py OBLINT_SECRETS — the occurrence masks are
    secrets here, which the engine-round audit derives internally)."""
    import jax
    import jax.numpy as jnp

    from grapevine_tpu.analysis.oblint import analyze
    from grapevine_tpu.oram import posmap as pmod
    from grapevine_tpu.oram.path_oram import OramConfig
    from grapevine_tpu.oram.posmap import derive_posmap_spec, init_posmap

    pm = derive_posmap_spec(16) if recursive else None
    cfg = OramConfig(height=4, value_words=4, n_blocks=16, posmap=pm)
    pm_state = jax.eval_shape(
        lambda: init_posmap(cfg, jax.random.PRNGKey(0))
    )
    b = 4

    def sds(*sh, dt=jnp.uint32):
        return jax.ShapeDtypeStruct(sh, dt)

    def run(pm_state, idxs, new_leaves, dummy_leaves, first_occ,
            last_occ, pm_new_leaves, pm_dummy_leaves):
        return pmod.lookup_remap_round(
            cfg, pm_state, idxs, new_leaves, dummy_leaves,
            first_occ, last_occ,
            pm_new_leaves=pm_new_leaves if recursive else None,
            pm_dummy_leaves=pm_dummy_leaves if recursive else None,
            occ_impl=occ_impl, sort_impl=sort_impl,
        )

    return analyze(
        run,
        {"pm_state": pm_state, "idxs": sds(b), "new_leaves": sds(b),
         "dummy_leaves": sds(b), "first_occ": sds(b, dt=jnp.bool_),
         "last_occ": sds(b, dt=jnp.bool_), "pm_new_leaves": sds(b),
         "pm_dummy_leaves": sds(b)},
        secrets=pmod.OBLINT_SECRETS,
        allowlist=allowlist,
        name=f"lookup_remap/{occ_impl}_{sort_impl}_"
             f"{'rec' if recursive else 'flat'}",
    )


def census_variants(ecfg):
    """Adversarially different CONCRETE batches for the program-equality
    check: the full engine round must trace to the identical program
    whatever the ops are (the legacy checkers' constants-baked-in
    stance, lifted to the whole round)."""
    import numpy as np

    from grapevine_tpu.engine.state import (
        ID_WORDS, KEY_WORDS, PAYLOAD_WORDS,
    )

    b = ecfg.batch_size

    def batch(rt, fill):
        rng = np.random.default_rng(fill + 1)

        def col(w):
            return (
                rng.integers(1, 2**31, (b, w)).astype(np.uint32)
                if fill else np.zeros((b, w), np.uint32)
            )

        return {
            "req_type": np.full((b,), rt, np.uint32),
            "auth": col(KEY_WORDS), "msg_id": col(ID_WORDS),
            "recipient": col(KEY_WORDS), "payload": col(PAYLOAD_WORDS),
            "now": np.uint32(1000), "now_hi": np.uint32(0),
        }

    dup = batch(1, fill=3)
    dup["recipient"][:] = dup["recipient"][0]  # every op same recipient
    dup["msg_id"][:] = dup["msg_id"][0]
    out = {
        "all_padding": batch(0, fill=0),
        "all_create": batch(1, fill=1),
        "all_read_dup_ids": dup,
        "mixed": {**batch(2, fill=2),
                  "req_type": (np.arange(b) % 5).astype(np.uint32)},
    }
    # device constants, not host ndarrays: the engine indexes batch
    # columns with traced values, which numpy arrays reject
    import jax.numpy as jnp

    return {
        vname: {k: jnp.asarray(v) for k, v in b.items()}
        for vname, b in out.items()
    }


def census_equal_engine(ecfg, name: str):
    import jax

    from grapevine_tpu.analysis.oblint import census_equal
    from grapevine_tpu.engine import round_step
    from grapevine_tpu.engine.state import init_engine

    state = jax.eval_shape(lambda: init_engine(ecfg, 0))
    variants = {
        vname: (
            lambda st, b=b: round_step.engine_round_step(ecfg, st, b),
            (state,),
        )
        for vname, b in census_variants(ecfg).items()
    }
    return census_equal(variants, name=f"engine_round/{name}")


def run_mutant_controls(allowlist) -> list:
    """Every seeded mutant must FAIL under the production allowlists.

    One shared runner for BOTH analyzers (ISSUE 14): the oblint leak
    mutants under the taint allowlist and the rangelint overflow mutants
    under the range allowlist — a single tier-1 gate proves both
    analyzers still have teeth."""
    from grapevine_tpu.analysis.allowlist import RANGE_ALLOWLIST
    from grapevine_tpu.analysis.mutants import (
        control_failures, run_mutants, run_range_mutants,
    )

    log = lambda line: print(f"[check_oblivious] {line}")  # noqa: E731
    return control_failures(
        run_mutants(allowlist), "mutant", log
    ) + control_failures(
        run_range_mutants(RANGE_ALLOWLIST), "range mutant", log
    )


def run_locklint() -> list:
    from grapevine_tpu.analysis.locklint import lint_repo

    vs = lint_repo(os.path.join(REPO, "grapevine_tpu"))
    for v in vs:
        print(f"[check_oblivious] locklint VIOLATION {v}")
    return [str(v) for v in vs]


def run_audit(combos, allowlist=None, with_census="first",
              with_subrounds: bool = True, verbose: bool = False):
    """Sweep the taint audit; returns (problems, allowlist_hits).

    ``with_census``: "first" = program-equality on the lead combo (the
    default tier), "all" = on every combo (--full), False = skip."""
    from grapevine_tpu.analysis.allowlist import ENGINE_ALLOWLIST

    if allowlist is None:
        allowlist = ENGINE_ALLOWLIST
    problems: list = []
    hits: dict = {}

    def absorb(rep):
        for k, n in rep.allowed.items():
            hits[k] = hits.get(k, 0) + n
        if verbose or rep.violations:
            print(rep.summary())
        problems.extend(f"{rep.name}: {v}" for v in rep.violations)

    for vp, srt, pmi, k, ee in combos:
        name = f"{vp}_{srt}_{pmi}_k{k}_e{ee}"
        absorb(audit_engine_round(_small_engine(vp, srt, pmi, k, ee),
                                  allowlist, name))
        absorb(audit_expiry_sweep(_small_engine(vp, srt, pmi, k, ee),
                                  allowlist, name))
        if ee > 1:
            # the write half of the delayed round: the flush program
            # audits standalone (it runs as its own dispatch)
            absorb(audit_engine_flush(_small_engine(vp, srt, pmi, k, ee),
                                      allowlist, name))
        if with_subrounds:
            absorb(audit_oram_round(
                allowlist, occ_impl=vp, sort_impl=srt,
                recursive=(pmi == "recursive"), k=k, ee=ee,
            ))
            absorb(audit_lookup_remap(
                allowlist, occ_impl=vp, sort_impl=srt,
                recursive=(pmi == "recursive"),
            ))
            if ee > 1:
                absorb(audit_oram_flush(
                    allowlist, sort_impl=srt,
                    recursive=(pmi == "recursive"), k=k, ee=ee,
                ))
                import jax

                if len(jax.devices()) >= 2:
                    # the mesh composition of the same flush (ISSUE
                    # 18): owner-masked scatter on a 2-shard mesh
                    absorb(audit_sharded_oram_flush(
                        allowlist, sort_impl=srt,
                        recursive=(pmi == "recursive"), k=k, ee=ee,
                        shards=2,
                    ))
                else:  # pragma: no cover - bootstrap in main()
                    problems.append(
                        "sharded flush audit needs >= 2 devices (got "
                        "1) — run standalone (main() forces a virtual "
                        "2-device CPU mesh) or under the test "
                        "harness's 8-device conftest"
                    )
    if with_census:
        census_combos = combos if with_census == "all" else combos[:1]
        for vp, srt, pmi, k, ee in census_combos:
            for v in census_equal_engine(
                _small_engine(vp, srt, pmi, k, ee),
                f"{vp}_{srt}_{pmi}_k{k}_e{ee}",
            ):
                problems.append(str(v))
    return problems, hits


def check_allowlist_reachability(hits: dict) -> list:
    """Every reviewed entry must fire somewhere in the sweep."""
    from grapevine_tpu.analysis.allowlist import ENGINE_ALLOWLIST

    dead = [e for e in ENGINE_ALLOWLIST if e.key not in hits]
    return [
        f"dead allowlist entry {e.key!r} ({e.reason!r}): never reached "
        "in any swept knob combination — delete it or sweep the combo "
        "that exercises it (dead entries rot into blanket permissions)"
        for e in dead
    ]


def main(argv=None) -> int:
    import argparse
    import itertools

    # the sharded flush audit traces a 2-device shard_map: force a
    # virtual CPU mesh if jax has not initialized yet (standalone
    # invocation; in-process the test conftest already forces 8)
    if ("jax" not in sys.modules
            and "xla_force_host_platform_device_count"
            not in os.environ.get("XLA_FLAGS", "")):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=2"
        ).strip()

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tier-1 budget: one combo, engine trace + "
                         "mutants + locklint; no census sweep, no "
                         "reachability check")
    ap.add_argument("--full", action="store_true",
                    help="full 2x2x2x2 knob cross-product + census "
                         "equality on every combo (the -m slow tier)")
    ap.add_argument("--skip-mutants", action="store_true")
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)

    from grapevine_tpu.analysis.allowlist import ENGINE_ALLOWLIST

    problems: list = []
    if args.smoke:
        vp, srt, pmi, k, ee = SMOKE_COMBO
        rep = audit_engine_round(
            _small_engine(vp, srt, pmi, k, ee), ENGINE_ALLOWLIST,
            f"{vp}_{srt}_{pmi}_k{k}_e{ee}",
        )
        print(rep.summary())
        problems.extend(f"{rep.name}: {v}" for v in rep.violations)
    else:
        combos = (
            tuple(itertools.product(
                ("dense", "scan"), ("xla", "radix"),
                ("flat", "recursive"), (0, 2), (1, 2),
            ))
            if args.full else DEFAULT_COMBOS
        )
        swept, hits = run_audit(
            combos, with_census="all" if args.full else "first",
            with_subrounds=True, verbose=args.verbose,
        )
        problems.extend(swept)
        problems.extend(check_allowlist_reachability(hits))

    if not args.skip_mutants:
        problems.extend(run_mutant_controls(ENGINE_ALLOWLIST))
    problems.extend(run_locklint())

    if problems:
        print(f"[check_oblivious] FAIL: {len(problems)} problem(s)")
        for p in problems:
            print(f"  - {p}")
        return 1
    scope = (
        "smoke combo" if args.smoke
        else "full knob matrix" if args.full else "shipped knob matrix"
    )
    reach = "" if args.smoke else "; every allowlist entry reachable"
    teeth = "" if args.skip_mutants else "; all mutants caught"
    print(f"[check_oblivious] PASS ({scope}): no secret-derived access "
          f"decision outside the reviewed allowlist{reach}{teeth}; "
          "lock discipline holds")
    return 0


if __name__ == "__main__":
    sys.exit(main())
