#!/usr/bin/env python
"""Perf-regression sentinel over the banked bench trajectory.

BENCH_trajectory.jsonl accumulates one JSON line per bench run across
PRs (bench.py --pr TAG). This tool turns that record into a gate: it
flattens every run's ``configs`` tree into directional series —
throughputs (higher is better) and latencies (lower is better), keyed
by config, metric path, and the run's geometry/sizes/backend so toy
smoke shapes are never compared against full-size runs — and fails
when a fresh observation regresses beyond a noise factor against the
**median** of the previously banked values of the SAME series.

Median, not best: this sandbox's 2-vCPU scheduler noise puts
back-to-back medians up to 2× apart (PERF.md Round 6 methodology
note), so judging against the best-ever banked value would ratchet
the bar toward the luckiest historical observation and fail tier-1
spuriously as lines accumulate. The median of history is stable under
that noise, and the default ``--factor 2.0`` (fail only past 2× of
the median) matches the sentinel's actual purpose — catching the
2-10× regressions an accidental algorithmic change causes (a
quadratic sneaking back in, a donation lost to a defensive copy), not
10% drift. Tighten ``--factor`` on quiet hardware.

Modes:

- ``--smoke`` (the tier-1 gate, wired next to check_telemetry_policy /
  check_checkpoint_seal): no bench run — milliseconds, not minutes.
  Three checks: the trajectory parses into comparable series; the
  LATEST observation of every series that repeats is within the factor
  of its prior median (the banked baseline polices itself); and a
  synthetic self-test proves the comparator actually fires on a clear
  regression and stays quiet inside the factor (a sentinel that cannot
  fail is not a sentinel).
- ``--fresh FILE`` (or ``-`` for stdin): compare a fresh bench.py
  output line against the banked baselines — the A/B workflow PERF.md
  points future perf PRs at. Exit 1 on any regression past the factor.
- ``--run``: execute ``bench.py --smoke`` in a subprocess and compare
  its output (slow; for local use, never tier-1).

Run directly::

    python tools/check_perf_regression.py --smoke
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRAJECTORY = os.path.join(REPO, "BENCH_trajectory.jsonl")

#: metric-name suffixes with a known direction. Anything else (counts,
#: notes, verdict strings, speedup ratios — already a comparison) is
#: not gated.
HIGHER_BETTER = ("ops_per_sec", "records_per_sec")
LOWER_BETTER = ("_ms", "_ms_per_op", "_s")

#: config fields that describe geometry, not performance — they key the
#: series (comparing B=8 smoke against B=2048 full would be noise, not
#: signal) and are excluded from the metrics themselves
GEOMETRY_KEYS = ("batch", "capacity_log2", "mesh", "clients",
                 "tree_density", "key_bits", "radix_bits_per_pass",
                 "rounds", "slo_target_ms", "pipeline_depth",
                 "evict_every", "shard_count", "tail_frames",
                 "worker_count", "adaptive_batch", "crypto_backend",
                 "host_cores", "verify_items")

#: result fields that are neither geometry nor a directional metric.
#: dispatch_skew_p99_ms is the load harness's HONESTY metric (how late
#: the replay dispatcher ran) — a property of the measuring host, not
#: of the engine; knee_target_ms is the host-CALIBRATED knee SLO
#: target (max(250, 8x unloaded round)) — config derived from a
#: measurement, neither geometry (it would fragment every capacity
#: series) nor a directional metric. Neither gates.
SKIP_KEYS = ("note", "skipped", "error", "leakaudit", "verdict",
             "interpret_trace_s", "compile_s", "wall_s",
             "dispatch_skew_p99_ms", "calibrated_round_ms",
             "knee_target_ms")


def _direction(name: str) -> int:
    """+1 higher-better, -1 lower-better, 0 not gated."""
    if name.endswith(HIGHER_BETTER):
        return 1
    if name.endswith(LOWER_BETTER) and not name.startswith("speedup"):
        return -1
    return 0


def _flatten(prefix: str, node, out: dict) -> None:
    if isinstance(node, dict):
        for k, v in sorted(node.items()):
            if k in SKIP_KEYS or k in GEOMETRY_KEYS:
                continue
            _flatten(f"{prefix}.{k}" if prefix else str(k), v, out)
        return
    if isinstance(node, (int, float)) and not isinstance(node, bool):
        d = _direction(prefix.rsplit(".", 1)[-1])
        if d and node > 0:  # zero = unmeasured placeholder, not a perf
            out[prefix] = (float(node), d)


def _geometry_sig(cfg_result: dict) -> str:
    if not isinstance(cfg_result, dict):
        return ""
    return ",".join(
        f"{k}={cfg_result[k]}" for k in GEOMETRY_KEYS if k in cfg_result
    )


def extract_series(lines: list[dict]) -> dict:
    """{series_key: [(tag, value, direction), ...]} in banked order.

    A series key is (config, metric path, geometry, sizes, backend) —
    observations are only comparable inside one key.
    """
    series: dict = {}
    for line in lines:
        sizes = line.get("sizes", "?")
        backend = line.get("backend", "?")
        tag = line.get("pr", "") or str(line.get("ts", "?"))
        for cfg_name, cfg_result in (line.get("configs") or {}).items():
            if not isinstance(cfg_result, dict):
                continue
            if "skipped" in cfg_result or "error" in cfg_result:
                continue
            flat: dict = {}
            _flatten("", cfg_result, flat)
            sig = _geometry_sig(cfg_result)
            for path, (value, d) in flat.items():
                key = f"{cfg_name}.{path}|{sig}|{sizes}|{backend}"
                series.setdefault(key, []).append((tag, value, d))
    return series


def _median(values: list[float]) -> float:
    s = sorted(values)
    n = len(s)
    return s[n // 2] if n % 2 else (s[n // 2 - 1] + s[n // 2]) / 2.0


def compare_latest(series: dict, factor: float) -> tuple[list, int]:
    """Check each repeating series' newest value against the MEDIAN of
    its earlier ones (robust to one lucky banked outlier). Returns
    (regressions, n_compared)."""
    regressions = []
    compared = 0
    for key, obs in series.items():
        if len(obs) < 2:
            continue
        *hist, (tag, value, d) = obs
        compared += 1
        base = _median([v for _, v, _ in hist])
        if d > 0:
            if value * factor < base:
                regressions.append(
                    f"{key}: {value:g} is {value / base:.2f}x of the "
                    f"banked median {base:g} (allowed ≥ 1/{factor:g}x; "
                    f"latest tag {tag!r})"
                )
        else:
            if value > base * factor:
                regressions.append(
                    f"{key}: {value:g} is {value / base:.2f}x of the "
                    f"banked median {base:g} (allowed ≤ {factor:g}x; "
                    f"latest tag {tag!r})"
                )
    return regressions, compared


def compare_fresh(fresh_line: dict, banked: list[dict],
                  factor: float) -> tuple[list, int]:
    """Compare one fresh bench line against the banked median per
    series."""
    base = extract_series(banked)
    fresh = extract_series([fresh_line])
    merged = {}
    for key, obs in fresh.items():
        if key in base:
            merged[key] = base[key] + obs
    return compare_latest(merged, factor)


def load_trajectory(path: str = TRAJECTORY) -> list[dict]:
    lines = []
    with open(path, encoding="utf-8") as fh:
        for i, raw in enumerate(fh, 1):
            raw = raw.strip()
            if not raw:
                continue
            try:
                lines.append(json.loads(raw))
            except json.JSONDecodeError as e:
                raise SystemExit(
                    f"{os.path.basename(path)}:{i}: unparseable banked "
                    f"line ({e}) — the baseline record is corrupt"
                ) from None
    if not lines:
        raise SystemExit(f"{path}: no banked bench lines")
    return lines


def selftest(factor: float) -> None:
    """The comparator must fire on a clear regression and stay quiet
    within the factor — run on synthetic lines so the check cannot rot."""
    mk = lambda ops, p99: {  # noqa: E731
        "sizes": "full", "backend": "cpu", "pr": "synthetic",
        "configs": {"synth": {"ops_per_sec": ops, "p99_round_ms": p99,
                              "batch": 8, "capacity_log2": 10}},
    }
    regs, n = compare_latest(
        extract_series([mk(100.0, 50.0),
                        mk(100.0 / (factor * 2.0),
                           50.0 * factor * 2.0)]),
        factor,
    )
    assert n == 2 and len(regs) == 2, (
        f"sentinel self-test: past-factor regression not flagged ({regs})"
    )
    drift = 1.0 + (factor - 1.0) * 0.5  # halfway inside the factor
    regs, n = compare_latest(
        extract_series([mk(100.0, 50.0),
                        mk(100.0 / drift, 50.0 * drift)]), factor)
    assert n == 2 and not regs, (
        f"sentinel self-test: within-factor drift flagged ({regs})"
    )
    # geometry guard: same metric at a different batch is NOT compared
    a = mk(100.0, 50.0)
    b = mk(1.0, 5000.0)
    b["configs"]["synth"]["batch"] = 2048
    regs, n = compare_latest(extract_series([a, b]), factor)
    assert n == 0 and not regs, (
        "sentinel self-test: mismatched geometry was compared"
    )
    # the capacity metric path (PR 9, bench load_scenarios): the knee
    # and per-scenario throughput/latency nest two dicts deep — the
    # flattener must produce comparable series for them, fire past the
    # factor, and skip the honesty/calibration fields. knee_target_ms
    # VARIES between the two synthetic lines on purpose: it is
    # perf_counter-calibrated in real runs, and were it geometry (or a
    # gated metric) every run would mint a fresh series and the
    # capacity numbers would never be compared at all.
    mk_cap = lambda knee, p99, tgt: {  # noqa: E731
        "sizes": "full", "backend": "cpu", "pr": "synthetic",
        "configs": {"load_scenarios": {
            "batch": 16, "capacity_log2": 14, "knee_target_ms": tgt,
            "knee_ops_per_sec": knee,
            "scenarios": {"steady": {
                "achieved_ops_per_sec": knee * 0.5,
                "p99_commit_ms": p99,
                "dispatch_skew_p99_ms": p99 * 100.0,  # must NOT gate
                "leakaudit": "PASS",
            }},
        }},
    }
    regs, n = compare_latest(
        extract_series([mk_cap(200.0, 40.0, 3250.7),
                        mk_cap(200.0 / (factor * 2.0),
                               40.0 * factor * 2.0, 2871.3)]),
        factor,
    )
    assert n == 3 and len(regs) == 3, (
        f"sentinel self-test: capacity series not gated ({n=}, {regs}) "
        "— a calibration-varying field fragmented the series keys?"
    )
    assert not any("dispatch_skew" in r or "knee_target" in r
                   for r in regs), (
        "sentinel self-test: an honesty/calibration field was gated"
    )
    regs, n = compare_latest(
        extract_series([mk_cap(200.0, 40.0, 3250.7),
                        mk_cap(200.0, 40.0, 2871.3)]), factor)
    assert n == 3 and not regs, (
        f"sentinel self-test: steady capacity series flagged ({regs})"
    )
    # pipeline_depth is GEOMETRY (PR 10): an explicit-depth rerun keys
    # its own series — a depth-2 knee must never be graded against the
    # auto/depth-1 baseline (they measure different programs), and the
    # auto runs (no key at all) must stay one continuous series
    a = mk_cap(200.0, 40.0, 3250.7)
    b = mk_cap(200.0 / (factor * 4.0), 40.0 * factor * 4.0, 3250.7)
    b["configs"]["load_scenarios"]["pipeline_depth"] = 2
    regs, n = compare_latest(extract_series([a, b]), factor)
    assert n == 0 and not regs, (
        "sentinel self-test: a depth-keyed capacity line was compared "
        "against the auto-depth baseline"
    )
    # evict_every is GEOMETRY (PR 15): an E-keyed line (delayed batched
    # eviction — amortized flush, a different round program whose
    # steady-state cost is legitimately ~the fetch half) must never
    # grade against the E=1 series, in either direction; same-E lines
    # must still gate each other.
    a = mk_cap(200.0, 40.0, 3250.7)
    b = mk_cap(200.0 * factor * 4.0, 40.0 / (factor * 4.0), 3250.7)
    b["configs"]["load_scenarios"]["evict_every"] = 4
    regs, n = compare_latest(extract_series([a, b]), factor)
    assert n == 0 and not regs, (
        "sentinel self-test: an evict_every-keyed line was compared "
        "against the E=1 baseline"
    )
    c = mk_cap(200.0 * factor * 4.0, 40.0 / (factor * 4.0), 3250.7)
    d = mk_cap(200.0, 40.0, 3250.7)
    c["configs"]["load_scenarios"]["evict_every"] = 4
    d["configs"]["load_scenarios"]["evict_every"] = 4
    regs, n = compare_latest(extract_series([c, d]), factor)
    assert n == 3 and len(regs) == 3, (
        f"sentinel self-test: same-E series not gated ({n=}, {regs})"
    )
    # shard_count is GEOMETRY (PR 16, bench fleet_loopback): an N=2
    # fleet capacity line sums two shard knees over two engines — a
    # different deployment shape whose numbers must never grade against
    # the N=1 (monolithic) series, in either direction; same-N fleet
    # lines must still gate each other.
    a = mk_cap(200.0, 40.0, 3250.7)
    b = mk_cap(200.0 * factor * 4.0, 40.0 / (factor * 4.0), 3250.7)
    b["configs"]["load_scenarios"]["shard_count"] = 2
    regs, n = compare_latest(extract_series([a, b]), factor)
    assert n == 0 and not regs, (
        "sentinel self-test: a shard_count-keyed fleet line was "
        "compared against the single-process baseline"
    )
    e = mk_cap(200.0, 40.0, 3250.7)
    f = mk_cap(200.0 / (factor * 4.0), 40.0 * factor * 4.0, 3250.7)
    e["configs"]["load_scenarios"]["shard_count"] = 2
    f["configs"]["load_scenarios"]["shard_count"] = 2
    regs, n = compare_latest(extract_series([e, f]), factor)
    assert n == 3 and len(regs) == 3, (
        f"sentinel self-test: same-shard-count series not gated "
        f"({n=}, {regs})"
    )
    # tail_frames is GEOMETRY (ISSUE 19, bench failover_ab): the
    # measured failover RTO scales with the durable tail the promotion
    # replays, so a line banked at a different checkpoint interval is
    # a different experiment — never graded against another interval's
    # baseline, in either direction; same-interval lines must still
    # gate each other (an RTO regression at a FIXED tail is real).
    a = mk_cap(200.0, 40.0, 3250.7)
    b = mk_cap(200.0 * factor * 4.0, 40.0 / (factor * 4.0), 3250.7)
    b["configs"]["load_scenarios"]["tail_frames"] = 64
    regs, n = compare_latest(extract_series([a, b]), factor)
    assert n == 0 and not regs, (
        "sentinel self-test: a tail_frames-keyed failover line was "
        "compared against a different-interval baseline"
    )
    g = mk_cap(200.0, 40.0, 3250.7)
    h = mk_cap(200.0 / (factor * 4.0), 40.0 * factor * 4.0, 3250.7)
    g["configs"]["load_scenarios"]["tail_frames"] = 64
    h["configs"]["load_scenarios"]["tail_frames"] = 64
    regs, n = compare_latest(extract_series([g, h]), factor)
    assert n == 3 and len(regs) == 3, (
        f"sentinel self-test: same-tail-frames series not gated "
        f"({n=}, {regs})"
    )
    # worker_count is GEOMETRY (ISSUE 20, bench host_pipeline_ab): a
    # W-worker multiprocess frontend runs a different host program
    # (fan-out + IPC) than the in-process path — its numbers key their
    # own series in either direction; same-W lines must still gate.
    a = mk_cap(200.0, 40.0, 3250.7)
    b = mk_cap(200.0 * factor * 4.0, 40.0 / (factor * 4.0), 3250.7)
    b["configs"]["load_scenarios"]["worker_count"] = 2
    regs, n = compare_latest(extract_series([a, b]), factor)
    assert n == 0 and not regs, (
        "sentinel self-test: a worker_count-keyed host-pipeline line "
        "was compared against the in-process baseline"
    )
    i = mk_cap(200.0, 40.0, 3250.7)
    j = mk_cap(200.0 / (factor * 4.0), 40.0 * factor * 4.0, 3250.7)
    i["configs"]["load_scenarios"]["worker_count"] = 2
    j["configs"]["load_scenarios"]["worker_count"] = 2
    regs, n = compare_latest(extract_series([i, j]), factor)
    assert n == 3 and len(regs) == 3, (
        f"sentinel self-test: same-worker-count series not gated "
        f"({n=}, {regs})"
    )
    # adaptive_batch is GEOMETRY (ISSUE 20): the SLO-adaptive window
    # trades latency against occupancy per-round — a run with the
    # policy on measures a different collection discipline than the
    # static window and must never grade against it.
    a = mk_cap(200.0, 40.0, 3250.7)
    b = mk_cap(200.0 * factor * 4.0, 40.0 / (factor * 4.0), 3250.7)
    b["configs"]["load_scenarios"]["adaptive_batch"] = True
    regs, n = compare_latest(extract_series([a, b]), factor)
    assert n == 0 and not regs, (
        "sentinel self-test: an adaptive-batch line was compared "
        "against the static-window baseline"
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tier-1 mode: validate the banked baseline + "
                    "comparator self-test; no bench run")
    ap.add_argument("--fresh", metavar="FILE",
                    help="fresh bench.py JSON line to compare against "
                    "the banked baselines ('-' = stdin)")
    ap.add_argument("--run", action="store_true",
                    help="run bench.py --smoke and compare its output")
    ap.add_argument("--factor", type=float, default=2.0,
                    help="multiple of the banked median beyond which a "
                    "regression fails (default 2.0 — see the noise "
                    "rationale above; tighten on quiet hardware)")
    ap.add_argument("--trajectory", default=TRAJECTORY)
    args = ap.parse_args(argv)
    if args.factor <= 1.0:
        raise SystemExit("--factor must be > 1")

    selftest(args.factor)
    banked = load_trajectory(args.trajectory)
    series = extract_series(banked)
    if not series:
        raise SystemExit(
            "no comparable series in the trajectory — every banked line "
            "is skipped/errored or carries no directional metrics"
        )

    if args.fresh or args.run:
        if args.run:
            import subprocess

            out = subprocess.run(
                [sys.executable, os.path.join(REPO, "bench.py"), "--smoke"],
                capture_output=True, text=True, timeout=1800, cwd=REPO,
            )
            candidates = [ln for ln in out.stdout.splitlines()
                          if ln.strip().startswith("{")]
            if out.returncode != 0 or not candidates:
                raise SystemExit(
                    f"bench run failed (rc={out.returncode}): "
                    f"{out.stderr[-300:]}"
                )
            fresh_line = json.loads(candidates[-1])
        elif args.fresh == "-":
            fresh_line = json.loads(sys.stdin.read())
        else:
            with open(args.fresh, encoding="utf-8") as fh:
                fresh_line = json.loads(fh.read())
        regs, n = compare_fresh(fresh_line, banked, args.factor)
        scope = "fresh-vs-banked-median"
    else:
        regs, n = compare_latest(series, args.factor)
        scope = "banked-latest-vs-median"

    for r in regs:
        print(f"PERF REGRESSION: {r}", file=sys.stderr)
    print(
        f"perf sentinel: self-test ok; {len(banked)} banked lines, "
        f"{len(series)} series, {n} compared ({scope}, factor "
        f"{args.factor:g}x); {'FAILED' if regs else 'clean'}"
    )
    return 1 if regs else 0


if __name__ == "__main__":
    raise SystemExit(main())
