#!/usr/bin/env python3
"""One-shot TPU evidence capture, ordered by verdict value.

The axon relay flaps across sessions (PROBELOG_r4/r5: dead for whole
rounds, up in r2) — so when a backend initializes, ONE serialized
process must harvest everything the round needs before the window
closes. Stages, most valuable first (VERDICT r4 next-round #1/#2/#5):

1. probe       — backend + device kind (proves the window was real)
2. headline    — zipf_mixed at B=2048 / 2^20: scan-fused throughput +
                 per-dispatch p99 (THE scoreboard number)
3. mosaic      — engine round bit-equality jnp vs pallas vs
                 pallas_fused ON TPU (first real Mosaic compile of all
                 three kernels)
4. pallas_perf — zipf_pallas_cipher + zipf_pallas_fused at full size
4b. vphases_perf — dense vs scan slot-order machinery A/B (decides the
                 per-backend vphases_impl default, incl. the B=4096
                 dense-memory-wall probe)
4c. sort_perf  — xla vs radix bounded-key sort engine A/B (decides the
                 device sort_impl default: serial-scatter-bound on CPU,
                 open question on TPU where scatters vectorize)
4d. posmap_perf — flat vs recursive position map A/B (prices the
                 recursive map's whole-round overhead on a real chip —
                 the capacity knob's cost side, OPERATIONS.md §13)
4e. tree_cache_perf — tree-top cache k-sweep (the on-chip decision
                 number for the tree_top_cache_levels auto default,
                 jnp + fused-Pallas pairs; OPERATIONS.md §14)
5. oblivious   — transcript equality + R/U/D timing z-scores from
                 TPU-executed rounds (tiny capacity; it is the compiled
                 schedule being tested, not scale)
6. trace       — jax.profiler trace of the headline round, to reconcile
                 PERF.md's ~5-10 ms model
6b. live_profile — the PR-6 runtime capture path on a real chip: an
                 engine serving through the scheduler with the round
                 tracer + SLO stack on, profiled via the same
                 ProfilerGate /profile?ms=N exposes — proves a live
                 deployment can be profiled without restart, and banks
                 the first device bubble ratio (the number that sizes
                 the pipelined-round refactor, ROADMAP item 2)
6c. load_perf  — ramp-to-knee under the real device round (the PR-9
                 workload observatory on a chip): open-loop ramp
                 through the scheduler with workload telemetry +
                 tracer on, banks the device capacity knee AND the
                 bubble ratio *under load* — the pair of numbers that
                 decides how much throughput the ROADMAP-item-2
                 pipelined-round refactor can actually buy (a knee set
                 by host phases pipelines away; one set by device
                 rounds does not)
7. fullbench   — bench.py end to end on the live backend (full pass
                 only): the driver-format artifact as a dress
                 rehearsal, and it warms the shared compilation cache
                 so the driver's own run never recompiles

Every stage appends one JSON line to --out (default TPURUN_r5.jsonl,
repo root) and flushes — a relay death mid-run keeps everything already
captured. Each stage runs in its OWN subprocess under a hard timeout:
a wedged device dispatch blocks in C++ where Python signal handlers
never run, so only a process kill can bound it (and the relay's
single-claim tunnel is released when the child dies). Heavy work is
serialized; nothing else should hold the tunnel while this runs.

Run: python tools/tpu_capture.py [--quick] [--skip STAGE,...]
``--quick`` shrinks the headline/pallas configs (B=256, 2^16) for a
short relay window; rerun without it if the window holds.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


class Capture:
    def __init__(self, out_path):
        self.out = open(out_path, "a", buffering=1)

    def emit(self, stage, **kv):
        line = {"stage": stage, "t": round(time.time(), 1), **kv}
        self.out.write(json.dumps(line) + "\n")
        self.out.flush()
        print(json.dumps(line), flush=True)


def stage_probe(cap, args):
    import jax
    import jax.numpy as jnp

    t0 = time.perf_counter()
    x = jnp.ones((256, 256), jnp.float32)
    (x @ x).block_until_ready()
    dev = jax.devices()[0]
    cap.emit("probe", backend=jax.default_backend(),
             device_kind=getattr(dev, "device_kind", str(dev)),
             n_devices=len(jax.devices()),
             init_s=round(time.perf_counter() - t0, 1))
    from grapevine_tpu.config import TPU_BACKENDS

    if jax.default_backend() not in TPU_BACKENDS:
        raise RuntimeError(f"not a TPU backend: {jax.default_backend()!r}")


def _zipf_run(cap, stage_name, impl, cap_log2, batch, n_rounds,
              vphases=None, sort=None, posmap=None, tree_cache=None):
    """zipf_mixed through a chosen cipher impl at a chosen size, using
    bench.py's own machinery (same methodology as the driver bench).
    ``vphases`` selects the slot-order machinery ("dense"/"scan"),
    ``sort`` the bounded-key sort engine ("xla"/"radix"), ``posmap``
    the position map ("flat"/"recursive"), ``tree_cache`` the tree-top
    cache depth (int; 0 = off); None = the backend default for each."""
    import jax
    import numpy as np

    import bench

    t0 = time.perf_counter()
    cfg, ecfg, state, step = bench._mk_engine(
        1 << cap_log2, 1 << max(8, cap_log2 - 8), batch, cipher_impl=impl,
        vphases_impl=vphases, sort_impl=sort, posmap_impl=posmap,
        tree_top_cache=tree_cache,
    )
    batches = bench.make_batches(4, batch)
    compile_t0 = time.perf_counter()
    state, resp, _ = step(ecfg, state, batches[0])
    jax.block_until_ready(resp)
    compile_s = time.perf_counter() - compile_t0
    _, times, total = bench._run_rounds(ecfg, state, step, batches[1:], n_rounds)
    ops = batch * n_rounds
    cap.emit(stage_name, impl=impl, vphases=ecfg.vphases_impl,
             sort=ecfg.sort_impl, posmap=ecfg.posmap_impl,
             tree_cache=ecfg.tree_top_cache_levels,
             capacity_log2=cap_log2, batch=batch,
             rounds=n_rounds, ops_per_sec=round(ops / total, 1),
             p99_round_ms=round(bench._p99(times), 2),
             median_round_ms=round(float(np.median(times)) * 1e3, 3),
             compile_s=round(compile_s, 1),
             wall_s=round(time.perf_counter() - t0, 1))


def stage_headline(cap, args):
    if args.quick:
        _zipf_run(cap, "headline", "jnp", 16, 256, 8)
        return
    # mid size first: it compiles faster, and B=2048 at 2^18 already
    # answers the batch-scaling question (window 1 banked B=256/2^16 at
    # 33 ms/round — flat-vs-linear in B decides the ops/s ceiling) even
    # if the window dies before the full-size run
    _zipf_run(cap, "headline", "jnp", 18, 2048, 8)
    _zipf_run(cap, "headline", "jnp", 20, 2048, 8)


def stage_micro(cap, args):
    """Component microbench at the headline geometry: decomposes the
    engine round into its device primitives so a short window still
    pinpoints the bottleneck (window 1: measured 33 ms/round at
    B=256/2^16 vs the ~2-5 ms analytic model — a 30x gap whose prime
    suspect is XLA:TPU's serial dynamic scatter on the tree write-back,
    the exact op the fused Pallas scatter kernel replaces)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from grapevine_tpu.oblivious.bucket_cipher import row_keystream

    cl, b = (16, 256) if args.quick else (20, 2048)
    plen = cl - 1 + 2  # tree levels at density 2, incl. root+leaf fringe
    n = 1 << (cl + 1)  # padded bucket count, density 2
    rows = b * plen
    w = 1020
    key = jnp.arange(8, dtype=jnp.uint32)
    # EVERYTHING device-generated: the relay tunnel moves ~10 MB/s, so
    # host-staging the 0.5-2 GB tree would eat the window on transfer
    prng = jax.random.PRNGKey(0)
    mk_tree = jax.jit(lambda: jnp.zeros((n, w), jnp.uint32))
    flat_b = jax.jit(
        lambda k: jax.random.permutation(k, n - 1)[:rows].astype(jnp.uint32)
    )(prng)
    new_rows = jax.jit(
        lambda: jax.lax.broadcasted_iota(jnp.uint32, (rows, w), 0) | 1
    )()
    sort_keys = jax.jit(
        lambda k: jax.random.bits(k, (rows * 8,)).astype(jnp.uint32)
    )(prng)
    epoch = jnp.ones((rows, 2), jnp.uint32)
    jax.block_until_ready((flat_b, new_rows, sort_keys, epoch))

    def timed(name, fn, *xs):
        f = jax.jit(fn)
        out = f(*xs)
        jax.block_until_ready(out)  # compile
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            out = f(*xs)
            jax.block_until_ready(out)
            ts.append(time.perf_counter() - t0)
        return name, round(float(np.median(ts)) * 1e3, 3)

    def timed_scatter(name, fn):
        # donate + carry the tree so the measurement is the in-place
        # scatter the engine round actually pays under its single jit,
        # not scatter + a full tree copy (a fresh tree per case: each
        # case's first call consumes its donated input)
        f = jax.jit(fn, donate_argnums=(0,))
        t = f(mk_tree(), flat_b, new_rows)
        jax.block_until_ready(t)  # compile (consumes the donated arg)
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            t = f(t, flat_b, new_rows)
            jax.block_until_ready(t)
            ts.append(time.perf_counter() - t0)
        return name, round(float(np.median(ts)) * 1e3, 3)

    res = dict([
        timed("gather_rows_ms", lambda t, i: t[i], mk_tree(), flat_b),
        timed_scatter("scatter_rows_ms",
                      lambda t, i, v: t.at[i].set(v)),
        timed_scatter("scatter_unique_ms",
                      lambda t, i, v: t.at[i].set(
                          v, mode="drop", unique_indices=True)),
        timed_scatter("scatter_sorted_ms",
                      lambda t, i, v: t.at[jnp.sort(i)].set(
                          v, mode="drop", unique_indices=True,
                          indices_are_sorted=True)),
        timed("argsort_ms", lambda k: jnp.argsort(k), sort_keys),
        timed("chacha_keystream_ms",
              lambda k, bkt, ep: row_keystream(k, bkt, ep, w, 8),
              key, flat_b, epoch),
        timed("xor_rows_ms", lambda a, v: a ^ v, new_rows, new_rows),
    ])
    cap.emit("micro", capacity_log2=cl, batch=b, path_rows=rows,
             row_words=w, **res)


def stage_mosaic(cap, args):
    """All three kernels Mosaic-compiled on TPU; engine round results +
    final state bit-identical across cipher impls (cipher ON), junk
    bucket excluded (see _state_equal_excluding_junk)."""
    import jax
    import numpy as np

    import bench

    outs = {}
    for impl in ("jnp", "pallas", "pallas_fused", "pallas_fused_tiled"):
        t0 = time.perf_counter()
        cfg, ecfg, state, step = bench._mk_engine(
            1 << 10, 1 << 6, 16, cipher_impl=impl
        )
        batches = bench.make_batches(3, 16)
        rs = []
        for b in batches:
            state, resp, tr = step(ecfg, state, b)
            rs.append(resp)
        jax.block_until_ready(rs[-1])
        outs[impl] = (
            [{k: np.asarray(v) for k, v in r.items()} for r in rs],
            jax.tree_util.tree_map(np.asarray, state),
        )
        cap.emit("mosaic_compile", impl=impl,
                 wall_s=round(time.perf_counter() - t0, 1))
    ok = True
    detail = {}
    for impl in ("pallas", "pallas_fused", "pallas_fused_tiled"):
        same = all(
            all(np.array_equal(outs["jnp"][0][i][k], outs[impl][0][i][k])
                for k in outs["jnp"][0][i])
            for i in range(len(outs["jnp"][0]))
        )
        from grapevine_tpu.testing.compare import states_equal_excluding_junk

        st_same, first_diff = states_equal_excluding_junk(
            outs["jnp"][1], outs[impl][1])
        detail[impl] = {"responses_equal": bool(same),
                        "state_equal_excl_junk_bucket": bool(st_same),
                        **({"first_diff": first_diff} if first_diff else {})}
        ok = ok and same and st_same
    cap.emit("mosaic", bit_identical=ok, detail=detail)
    if not ok:
        raise RuntimeError(f"Mosaic kernels diverge from jnp: {detail}")


def stage_pallas_perf(cap, args):
    cl, b = (16, 256) if args.quick else (20, 2048)
    # tiled first: per-step overhead makes it the best bet at full size
    _zipf_run(cap, "pallas_perf", "pallas_fused_tiled", cl, b, 8)
    _zipf_run(cap, "pallas_perf", "pallas_fused", cl, b, 8)
    _zipf_run(cap, "pallas_perf", "pallas", cl, b, 8)


def stage_vphases_perf(cap, args):
    """Dense vs scan slot-order machinery at headline geometry ON TPU —
    the A/B that decides the per-backend ``vphases_impl`` default
    (config.py; currently dense-on-TPU on the theory that the MXU eats
    the [B,B] masks and one-hot matmuls). Mirrors ``pallas_perf``:
    identical workload, the knob is the only difference, and the two
    impls are bit-identical (tests/test_vphases_scan.py) so whichever
    is faster simply wins. Also the B=4096 probe: the dense masks at
    B=4096 cost ~1.1 GB of [B,B]-shaped intermediates per round
    (PERF.md Round 6 memory math) — if dense OOMs or cliffs there while
    scan runs, that alone decides the large-B default."""
    cl, b = (16, 256) if args.quick else (20, 2048)
    _zipf_run(cap, "vphases_perf", "jnp", cl, b, 8, vphases="dense")
    _zipf_run(cap, "vphases_perf", "jnp", cl, b, 8, vphases="scan")
    if not args.quick:
        # the unlock question: scan at the batch size dense pins
        _zipf_run(cap, "vphases_perf", "jnp", 20, 4096, 8, vphases="scan")
        _zipf_run(cap, "vphases_perf", "jnp", 20, 4096, 8, vphases="dense")


def stage_sort_perf(cap, args):
    """xla vs radix bounded-key sort engine ON TPU — the A/B that
    decides the device ``sort_impl`` default (config.py; currently xla
    everywhere: on XLA:CPU the serial native sort wins because every
    radix pass pays a serial scatter, but on TPU scatters vectorize
    while lax.sort lowers to an O(n log² n) bitonic network — the open
    question only a real chip answers; PERF.md Round 7). Mirrors
    ``vphases_perf``: identical workload, the knob the only difference,
    bit-identical impls (tests/test_sort_radix.py) so the faster one
    simply wins. Runs under vphases "scan" so the bounded group sorts
    are in the round, plus one "dense" pair (the admission walk's
    grouping sort follows the knob under both impls), plus the isolated
    machinery A/B from bench ``sort_ab`` at device working-set sizes."""
    cl, b = (16, 256) if args.quick else (20, 2048)
    _zipf_run(cap, "sort_perf", "jnp", cl, b, 8, vphases="scan", sort="xla")
    _zipf_run(cap, "sort_perf", "jnp", cl, b, 8, vphases="scan", sort="radix")
    if not args.quick:
        _zipf_run(cap, "sort_perf", "jnp", cl, b, 8, vphases="dense",
                  sort="xla")
        _zipf_run(cap, "sort_perf", "jnp", cl, b, 8, vphases="dense",
                  sort="radix")
        # the isolated machinery numbers (min-of-N, both scopes) — the
        # clean separation the whole round dilutes with gather traffic
        import bench

        cap.emit("sort_perf", machinery=bench.bench_sort_ab(smoke=False))


def stage_posmap_perf(cap, args):
    """Flat vs recursive position map ON TPU — the real-chip decision
    number for ``posmap_impl`` (config.py; auto stays "flat" until this
    stage shows the recursive map's extra internal-ORAM round hides
    under the payload round's existing gather/scatter wall, or capacity
    forces the flip regardless — OPERATIONS.md §13). Mirrors
    ``sort_perf``: identical workload, the knob the only difference,
    bit-identical impls (tests/test_posmap_ab.py) so the overhead
    number is the whole story. Whole-round pairs at the headline
    geometry plus the isolated lookup machinery grid (with the
    private/HBM memory split) from bench ``posmap_ab``."""
    cl, b = (16, 256) if args.quick else (20, 2048)
    _zipf_run(cap, "posmap_perf", "jnp", cl, b, 8, posmap="flat")
    _zipf_run(cap, "posmap_perf", "jnp", cl, b, 8, posmap="recursive")
    if not args.quick:
        # the capacity regime the knob exists for: the biggest tree the
        # chip holds, where the flat table is at its most expensive
        _zipf_run(cap, "posmap_perf", "jnp", 24, 1024, 6, posmap="flat")
        _zipf_run(cap, "posmap_perf", "jnp", 24, 1024, 6,
                  posmap="recursive")
        # isolated machinery grid — position resolution priced alone
        import bench

        cap.emit("posmap_perf", machinery=bench.bench_posmap_ab(smoke=False))


def stage_tree_cache_perf(cap, args):
    """Tree-top cache k-sweep ON TPU — the real-chip decision number
    for the ``tree_top_cache_levels`` auto default (config.py; auto = 4
    everywhere on the strict-subtraction argument — the cache removes
    HBM gather/scatter rows and cipher work, it never trades
    algorithms — with the CPU A/B banked in PERF.md Round 10). Mirrors
    ``posmap_perf``: identical workload, k the only knob, bit-identical
    logical state at every k (tests/test_tree_cache.py) so the faster
    k simply wins; this stage prices where the TPU curve flattens
    (deeper k caches levels fewer paths share — diminishing rows cut
    per byte pinned). Pairs at headline geometry plus the isolated
    ORAM-round machinery grid from bench ``tree_cache_ab``."""
    cl, b = (16, 256) if args.quick else (20, 2048)
    _zipf_run(cap, "tree_cache_perf", "jnp", cl, b, 8, tree_cache=0)
    _zipf_run(cap, "tree_cache_perf", "jnp", cl, b, 8, tree_cache=4)
    if not args.quick:
        _zipf_run(cap, "tree_cache_perf", "jnp", cl, b, 8, tree_cache=2)
        _zipf_run(cap, "tree_cache_perf", "jnp", cl, b, 8, tree_cache=8)
        # the cache composes with the fused Pallas path (the TPU
        # production cipher): one fused pair proves the composed fast
        # path and prices it
        _zipf_run(cap, "tree_cache_perf", "pallas_fused_tiled", cl, b, 8,
                  tree_cache=0)
        _zipf_run(cap, "tree_cache_perf", "pallas_fused_tiled", cl, b, 8,
                  tree_cache=4)
        # isolated machinery grid — path traffic priced alone
        import bench

        cap.emit("tree_cache_perf",
                 machinery=bench.bench_tree_cache_ab(smoke=False))


def stage_oblivious(cap, args):
    """SURVEY §7 hard-part 2 on the real device: R/U/D transcript
    equality + timing uniformity, reusing the CPU suite's EXACT
    methodology (tests/test_round.py's same-message construction,
    tests/test_timing_uniformity.py's interleaved Mann-Whitney z) so
    the TPU result is directly comparable to the CI record."""
    import numpy as np

    sys.path.insert(0, os.path.join(_REPO, "tests"))
    import test_timing_uniformity as ttu

    from grapevine_tpu.config import GrapevineConfig
    from grapevine_tpu.engine.batcher import GrapevineEngine
    from grapevine_tpu.testing.leakcheck import timing_twosample_z
    from grapevine_tpu.wire import constants as C
    from grapevine_tpu.wire.records import QueryRequest, RequestRecord

    # --- transcript equality: R/U/D of the same message, identically
    # seeded engines (test_round.py::test_round_engine_rud_transcripts)
    small = GrapevineConfig(max_messages=64, max_recipients=8,
                            mailbox_cap=4, batch_size=4,
                            bucket_cipher_rounds=8)
    a_id, b_id = b"\x07" * 32, b"\x08" * 32
    now = 1_700_000_000

    def req(rt, auth, msg_id=C.ZERO_MSG_ID, recipient=C.ZERO_PUBKEY):
        return QueryRequest(
            request_type=rt, auth_identity=auth,
            auth_signature=b"\x01" * C.SIGNATURE_SIZE,
            record=RequestRecord(msg_id=msg_id, recipient=recipient))

    def fresh():
        e = GrapevineEngine(small, seed=11)
        (r,) = e.handle_queries(
            [req(C.REQUEST_TYPE_CREATE, a_id, recipient=b_id)], now)
        assert r.status_code == C.STATUS_CODE_SUCCESS
        return e, r.record.msg_id

    trs = {}
    for rt in (C.REQUEST_TYPE_READ, C.REQUEST_TYPE_UPDATE,
               C.REQUEST_TYPE_DELETE):
        e, mid = fresh()
        _, tr = e.handle_queries_with_transcript(
            [req(rt, b_id, msg_id=mid, recipient=b_id)], now + 1)
        trs[rt] = tr
    e, mid = fresh()
    _, tr_unauth = e.handle_queries_with_transcript(
        [req(C.REQUEST_TYPE_DELETE, b"\x09" * 32, msg_id=mid,
             recipient=b_id)], now + 1)
    eq_ru = bool(np.array_equal(trs[C.REQUEST_TYPE_READ],
                                trs[C.REQUEST_TYPE_UPDATE]))
    eq_rd = bool(np.array_equal(trs[C.REQUEST_TYPE_READ],
                                trs[C.REQUEST_TYPE_DELETE]))
    eq_fail = bool(np.array_equal(trs[C.REQUEST_TYPE_DELETE], tr_unauth))

    # --- timing: the CPU suite's interleaved measurement, on TPU
    eng, cfg = ttu._mk_engine()
    ids, recips, sender = ttu._populate(eng, cfg)
    times = ttu._measure(eng, cfg, ids, recips, sender)
    z_ru = round(float(timing_twosample_z(times["read"], times["update"])), 2)
    z_rd = round(float(timing_twosample_z(times["read"], times["delete"])), 2)
    z_ud = round(float(timing_twosample_z(times["update"], times["delete"])), 2)
    cap.emit(
        "oblivious",
        transcripts_equal={"read_update": eq_ru, "read_delete": eq_rd,
                           "failed_op_indistinguishable": eq_fail},
        mean_round_ms={k: round(float(np.mean(v)) * 1e3, 3)
                       for k, v in times.items()},
        timing_z={"read_vs_update": z_ru, "read_vs_delete": z_rd,
                  "update_vs_delete": z_ud},
        honest_threshold=ttu.HONEST_Z,
    )
    if not (eq_ru and eq_rd and eq_fail):
        raise RuntimeError("transcripts differ across R/U/D on TPU!")
    if max(abs(z_ru), abs(z_rd), abs(z_ud)) > ttu.HONEST_Z:
        raise RuntimeError("op-type timing signal detected on TPU!")


def stage_trace(cap, args):
    import jax
    import numpy as np

    import bench

    cl, b = (16, 256) if args.quick else (20, 2048)
    outdir = os.path.join(_REPO, "tpu_trace_r5")
    cfg, ecfg, state, step = bench._mk_engine(1 << cl, 1 << (cl - 8), b)
    batches = bench.make_batches(4, b)
    state, resp, _ = step(ecfg, state, batches[0])
    jax.block_until_ready(resp)
    times = []
    with jax.profiler.trace(outdir):
        for i in range(6):
            t0 = time.perf_counter()
            state, resp, _ = step(ecfg, state, batches[i % 4])
            jax.block_until_ready(resp)
            times.append(time.perf_counter() - t0)
    cap.emit("trace", trace_dir=outdir,
             median_round_ms=round(float(np.median(times)) * 1e3, 3))


def stage_live_profile(cap, args):
    """PR-6 observability on a live engine, device edition: serve
    rounds through the BatchScheduler with the tracer + SLO attached,
    trigger a ProfilerGate capture mid-traffic (the exact callable
    /profile?ms=N runs), and report the device bubble ratio — host
    phase timers cannot see inside the fused round program, so this
    ratio measured ON TPU is the first real evidence for sizing the
    double-buffered round pipeline (ROADMAP item 2 / Palermo)."""
    import threading

    import numpy as np

    from grapevine_tpu.config import GrapevineConfig
    from grapevine_tpu.engine.batcher import GrapevineEngine
    from grapevine_tpu.obs.profiler import ProfilerGate
    from grapevine_tpu.obs.slo import SloTracker
    from grapevine_tpu.obs.tracer import RoundTracer
    from grapevine_tpu.server.scheduler import BatchScheduler
    from grapevine_tpu.wire import constants as C
    from grapevine_tpu.wire.records import QueryRequest, RequestRecord

    cl, b = (14, 16) if args.quick else (18, 256)
    cfg = GrapevineConfig(max_messages=1 << cl, max_recipients=1 << 10,
                          batch_size=b)
    engine = GrapevineEngine(cfg)
    tracer = RoundTracer(capacity=256, registry=engine.metrics.registry)
    engine.attach_tracer(tracer)
    slo = SloTracker(registry=engine.metrics.registry)
    engine.attach_slo(slo)
    sched = BatchScheduler(engine, clock=lambda: 1_700_000_000)
    gate = ProfilerGate(outdir=os.path.join(_REPO, "tpu_live_profile"))
    stop = threading.Event()
    errs: list = []

    def traffic(j):
        me = bytes([j + 1]) * 32
        i = 0
        try:
            while not stop.is_set():
                # recipients rotate a wide pool: ms-scale TPU rounds
                # commit thousands of CREATEs over the capture window,
                # and a fixed recipient would hit the 62-message
                # mailbox cap mid-stage (the bench slo_loopback lesson)
                rcp = bytes([j + 2, (i % 251) + 1,
                             (i // 251) % 251]) + bytes(29)
                r = sched.submit(QueryRequest(
                    request_type=C.REQUEST_TYPE_CREATE, auth_identity=me,
                    auth_signature=b"\x01" * C.SIGNATURE_SIZE,
                    record=RequestRecord(
                        msg_id=C.ZERO_MSG_ID, recipient=rcp,
                        payload=bytes([i & 0xFF]) * C.PAYLOAD_SIZE)))
                assert r.status_code == C.STATUS_CODE_SUCCESS, r.status_code
                i += 1
        except Exception as e:  # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=traffic, args=(j,), daemon=True)
               for j in range(4)]
    try:
        for t in threads:
            t.start()
        time.sleep(3.0)  # warm: compile + settle into steady state
        result = gate.capture(ms=2000)  # the /profile?ms=2000 path
        time.sleep(1.0)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        sched.close()
    if errs:
        raise errs[0]
    n_files = sum(len(fs) for _, _, fs in os.walk(result["trace_dir"]))
    if n_files == 0:
        raise RuntimeError("profiler capture wrote no trace files")
    trace = tracer.chrome_trace()
    v = slo.verdict()
    # "device" spans end when the HOST observed readiness (resolve runs
    # after the next round's collection window under the pipelined
    # scheduler), so their duration is an upper bound on device-busy
    # time, not device time itself. The decision numbers are the bubble
    # ratio (host-blocked fraction, measured exactly) and the evict
    # wait (a lower bound on the device tail the host actually paid);
    # pure unpipelined device time comes from the trace/headline stages.
    dev = [e["dur"] for e in trace["traceEvents"]
           if e.get("name") == "grapevine/device"]
    ev = [e["dur"] for e in trace["traceEvents"]
          if e.get("name") == "grapevine/evict"]
    cap.emit("live_profile", capacity_log2=cl, batch=b,
             trace_dir=result["trace_dir"], trace_files=n_files,
             capture_ms=result["ms"],
             rounds_traced=trace["otherData"]["rounds_recorded_total"],
             bubble_ratio=trace["otherData"]["bubble_ratio"],
             median_device_window_ms=round(float(np.median(dev)) / 1e3, 3)
             if dev else None,
             median_evict_wait_ms=round(float(np.median(ev)) / 1e3, 3)
             if ev else None,
             slo_ok=v["ok"], slo_fast_burn=v["fast_burn_rate"])


def stage_load_perf(cap, args):
    """Ramp-to-knee under the real device round (PR 9; the TPU
    decision input for ROADMAP item 2). Same harness as ``bench.py
    load_scenarios``: calibrate the unloaded round, staircase offered
    load past the estimate open-loop (``submit_nowait`` — overload is
    measured, never self-throttled), grade each step against the
    commit SLO, and bank the knee together with the bubble ratio the
    tracer measured UNDER that load — the host/device balance at
    capacity is what prices double-buffered rounds."""
    from grapevine_tpu.config import GrapevineConfig
    from grapevine_tpu.engine.batcher import GrapevineEngine
    from grapevine_tpu.load import (
        ScenarioRunner,
        analyze_ramp,
        calibrate_unloaded_round,
        ramp_to_saturation,
        steady_poisson,
    )
    from grapevine_tpu.obs.tracer import RoundTracer
    from grapevine_tpu.obs.workload import WorkloadTelemetry
    from grapevine_tpu.server.scheduler import BatchScheduler

    cl, b = (14, 16) if args.quick else (18, 256)
    cfg = GrapevineConfig(max_messages=1 << cl, max_recipients=1 << 10,
                          batch_size=b)
    engine = GrapevineEngine(cfg)
    tracer = RoundTracer(capacity=512, registry=engine.metrics.registry)
    engine.attach_tracer(tracer)
    wl = WorkloadTelemetry(engine.metrics.registry, batch_size=b)
    engine.attach_workload(wl)

    # compile + the unloaded round — the shared knee methodology
    # (load/harness.py), so this stage and bench load_scenarios can
    # never diverge on the target formula
    t_round, est, target_ms = calibrate_unloaded_round(engine,
                                                       1_700_000_000)

    sched = BatchScheduler(engine, clock=lambda: 1_700_000_000)
    try:
        runner = ScenarioRunner(sched, n_idents=64, settle_timeout_s=180.0)
        # settle the scheduler pipeline before the graded ramp
        runner.run(steady_poisson(0.25 * est, 1.0, 7))
        # snapshot the fill histogram here: the banked mean_fill must
        # cover the GRADED ramp only, not the full-batch calibration
        # rounds or the quarter-rate settle run above
        fill_child = engine.metrics.registry.get(
            "grapevine_load_batch_fill").child()
        _, fill_sum0, fill_n0 = fill_child.state()
        # steps must dwarf the commit latency (≈ a couple of rounds)
        # or overload never expresses inside a step (bench.py rule)
        schedule = ramp_to_saturation(
            0.25 * est, 2.0, 5, max(2.0, 12.0 * t_round), 9)
        res = runner.run(schedule)
    finally:
        sched.close()
    ramp = analyze_ramp(schedule, res, target_ms)
    trace = tracer.chrome_trace()
    _, fill_sum, fill_n = fill_child.state()
    d_sum, d_n = fill_sum - fill_sum0, fill_n - fill_n0
    cap.emit(
        "load_perf", capacity_log2=cl, batch=b,
        calibrated_round_ms=round(t_round * 1e3, 2),
        knee_target_ms=round(target_ms, 1),
        knee_ops_per_sec=ramp["knee_ops_per_sec"],
        saturated=ramp["saturated"],
        first_failing_rate=ramp["first_failing_rate"],
        steps=ramp["steps"],
        bubble_ratio_under_load=trace["otherData"]["bubble_ratio"],
        utilization={k: round(v, 4) for k, v in wl.utilization().items()},
        p99_commit_ms=res.summary().get("p99_commit_ms"),
        mean_fill=round(d_sum / d_n, 3) if d_n else None,
    )


def stage_pipeline_perf(cap, args):
    """Round-pipeline depth A/B on the real device round (PR 10; the
    ROADMAP item-2 decision number). For each ``pipeline_depth`` in
    {1, 2}: an engine with durability ON (journal fsync every round —
    the barrier the pipeline is supposed to hide) serves a steady
    open-loop stream through the production scheduler; banked per arm:
    achieved throughput, commit p50/p99, the measured journal-span
    stats, and the bubble ratio UNDER that load. On a device-bound
    round the depth-2 arm should approach pure device cadence with the
    fsync fully overlapped; if the two arms tie, the round is so
    host-bound that the pipeline has nothing to hide behind — either
    way this is the number that decides the device default."""
    import shutil
    import tempfile

    import numpy as np

    from grapevine_tpu.config import DurabilityConfig, GrapevineConfig
    from grapevine_tpu.engine.batcher import GrapevineEngine
    from grapevine_tpu.load import (
        ScenarioRunner,
        calibrate_unloaded_round,
        steady_poisson,
    )
    from grapevine_tpu.obs.tracer import RoundTracer
    from grapevine_tpu.server.scheduler import BatchScheduler

    cl, b, dur = (14, 16, 4.0) if args.quick else (18, 256, 10.0)
    tmp = tempfile.mkdtemp(prefix="gv-pipeline-perf-")
    out = {"capacity_log2": cl, "batch": b}
    try:
        est = None
        for depth in (1, 2):
            cfg = GrapevineConfig(
                max_messages=1 << cl, max_recipients=1 << 10,
                batch_size=b, pipeline_depth=depth,
            )
            dcfg = DurabilityConfig(
                state_dir=os.path.join(tmp, f"d{depth}"),
                checkpoint_every_rounds=1 << 20,
                journal_fsync_every=1,
            )
            engine = GrapevineEngine(cfg, durability=dcfg)
            # calibrate EVERY arm (not just the first): the call warms
            # this engine's own jit wrapper, so neither arm pays its
            # first compile/trace inside the measured window — the
            # bench_pipeline_ab warm-up discipline. Only the FIRST
            # arm's estimate sets the offered rate, so both arms are
            # offered the same absolute stream and the A/B compares
            # depths, not draws.
            t_round, est_arm, _ = calibrate_unloaded_round(
                engine, 1_700_000_000)
            if est is None:
                est = est_arm
                out["calibrated_round_ms"] = round(t_round * 1e3, 2)
            # tracer attached AFTER calibration: the ring (and the
            # journal-span stats below) must cover the loaded run only,
            # symmetrically for both arms
            tracer = RoundTracer(capacity=2048,
                                 registry=engine.metrics.registry)
            engine.attach_tracer(tracer)
            sched = BatchScheduler(engine, clock=lambda: 1_700_000_000)
            try:
                runner = ScenarioRunner(sched, n_idents=64,
                                        settle_timeout_s=180.0)
                res = runner.run(steady_poisson(0.6 * est, dur, seed=29))
            finally:
                sched.close()
                engine.close()
            trace = tracer.chrome_trace()
            j_ms = tracer.span_durations_ms("journal")
            s = res.summary()
            out[f"depth{depth}"] = {
                "achieved_ops_per_sec": s.get("achieved_ops_per_sec"),
                "p99_commit_ms": s.get("p99_commit_ms"),
                "p50_commit_ms": s.get("p50_commit_ms"),
                "bubble_ratio_under_load":
                    trace["otherData"]["bubble_ratio"],
                "journal_p99_ms": round(float(np.percentile(
                    j_ms, 99, method="higher")), 3) if j_ms else None,
                "rounds": trace["otherData"]["rounds_recorded_total"],
            }
        d1, d2 = out["depth1"], out["depth2"]
        if d1["p99_commit_ms"] and d2["p99_commit_ms"]:
            out["p99_delta_ms_d1_minus_d2"] = round(
                d1["p99_commit_ms"] - d2["p99_commit_ms"], 2)
        cap.emit("pipeline_perf", **out)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def stage_evict_perf(cap, args):
    """Delayed-eviction cadence A/B on the real device round (PR 15;
    the ROADMAP item-1 decision number — the seventh banked-decision
    stage). For each ``evict_every`` in {1, 4}: an engine serves a
    steady open-loop stream through the production scheduler; banked
    per arm: achieved throughput, commit p50/p99, and the bubble ratio
    UNDER load — the E=4 arm's flush dispatches async behind the
    window's last round, so on a device-bound host the flush should
    ride the idle window the bubble ratio prices and the arm should
    approach fetch-only cadence. This is the number that settles the
    ``evict_every`` auto default (currently 1 on every backend)."""
    import numpy as np

    from grapevine_tpu.config import GrapevineConfig
    from grapevine_tpu.engine.batcher import GrapevineEngine
    from grapevine_tpu.load import (
        ScenarioRunner,
        calibrate_unloaded_round,
        steady_poisson,
    )
    from grapevine_tpu.obs.tracer import RoundTracer
    from grapevine_tpu.server.scheduler import BatchScheduler

    cl, b, dur = (14, 16, 4.0) if args.quick else (18, 256, 10.0)
    out = {"capacity_log2": cl, "batch": b}
    est = None
    for ee in (1, 4):
        cfg = GrapevineConfig(
            max_messages=1 << cl, max_recipients=1 << 10,
            batch_size=b, evict_every=ee,
        )
        engine = GrapevineEngine(cfg)
        # calibrate EVERY arm (warms each arm's own compile — the
        # pipeline_perf discipline); only the FIRST arm's estimate sets
        # the offered rate so both arms see the same absolute stream
        t_round, est_arm, _ = calibrate_unloaded_round(
            engine, 1_700_000_000)
        if est is None:
            est = est_arm
            out["calibrated_round_ms"] = round(t_round * 1e3, 2)
        tracer = RoundTracer(capacity=2048,
                             registry=engine.metrics.registry)
        engine.attach_tracer(tracer)
        sched = BatchScheduler(engine, clock=lambda: 1_700_000_000)
        try:
            runner = ScenarioRunner(sched, n_idents=64,
                                    settle_timeout_s=180.0)
            res = runner.run(steady_poisson(0.6 * est, dur, seed=31))
        finally:
            sched.close()
            engine.close()
        trace = tracer.chrome_trace()
        s = res.summary()
        h = engine.health()
        out[f"e{ee}"] = {
            "achieved_ops_per_sec": s.get("achieved_ops_per_sec"),
            "p99_commit_ms": s.get("p99_commit_ms"),
            "p50_commit_ms": s.get("p50_commit_ms"),
            "bubble_ratio_under_load":
                trace["otherData"]["bubble_ratio"],
            "rounds": trace["otherData"]["rounds_recorded_total"],
            "stash_overflow": h["stash_overflow"],
            "evict_buffer_occupancy": h.get("evict_buffer_occupancy"),
        }
    e1, e4 = out["e1"], out["e4"]
    if e1.get("achieved_ops_per_sec") and e4.get("achieved_ops_per_sec"):
        out["throughput_ratio_e4_over_e1"] = round(
            e4["achieved_ops_per_sec"] / e1["achieved_ops_per_sec"], 3)
    cap.emit("evict_perf", **out)


def stage_sharded_perf(cap, args):
    """Owner-masked sharded flush under load on the real mesh (ISSUE
    18; the ROADMAP item-1 composition number). Same methodology as
    evict_perf — a steady open-loop stream through the production
    scheduler per ``evict_every`` arm — but the engine runs
    ``shards > 1``: fetch rounds gather sharded tree ranges per chip
    and the batched flush's scatter+encrypt pass is owner-masked along
    the bucket axis, so each chip writes only its owned HBM rows.
    Banked per arm: achieved throughput, commit p50/p99, bubble ratio
    under load. The pair (throughput_ratio_e4_over_e1 here vs the
    single-chip number from evict_perf) is what decides whether the
    read-mostly cadence survives composition with the mesh on real
    ICI, or the replicated-plane psums eat the flush savings.

    Shard count: the largest power of two <= device count (capped at
    4, the campaign grid's edge); a single-device host banks an
    explicit skip instead of silently measuring shards=1."""
    import jax
    import numpy as np

    from grapevine_tpu.config import GrapevineConfig
    from grapevine_tpu.engine.batcher import GrapevineEngine
    from grapevine_tpu.load import (
        ScenarioRunner,
        calibrate_unloaded_round,
        steady_poisson,
    )
    from grapevine_tpu.obs.tracer import RoundTracer
    from grapevine_tpu.server.scheduler import BatchScheduler

    n_dev = len(jax.devices())
    if n_dev < 2:
        cap.emit("sharded_perf",
                 skipped=f"1 device visible (mesh needs >= 2); "
                         "re-run on a pod slice or with "
                         "XLA_FLAGS=--xla_force_host_platform_"
                         "device_count=N")
        return
    shards = 4 if n_dev >= 4 else 2
    cl, b, dur = (14, 16, 4.0) if args.quick else (18, 256, 10.0)
    out = {"capacity_log2": cl, "batch": b, "shards": shards,
           "n_devices": n_dev}
    est = None
    for ee in (1, 4):
        cfg = GrapevineConfig(
            max_messages=1 << cl, max_recipients=1 << 10,
            batch_size=b, evict_every=ee, shards=shards,
        )
        engine = GrapevineEngine(cfg)
        # calibrate EVERY arm (warms each arm's own compile); the
        # FIRST arm's estimate sets the offered rate so both arms see
        # the same absolute stream (the evict_perf discipline)
        t_round, est_arm, _ = calibrate_unloaded_round(
            engine, 1_700_000_000)
        if est is None:
            est = est_arm
            out["calibrated_round_ms"] = round(t_round * 1e3, 2)
        tracer = RoundTracer(capacity=2048,
                             registry=engine.metrics.registry)
        engine.attach_tracer(tracer)
        sched = BatchScheduler(engine, clock=lambda: 1_700_000_000)
        try:
            runner = ScenarioRunner(sched, n_idents=64,
                                    settle_timeout_s=180.0)
            res = runner.run(steady_poisson(0.6 * est, dur, seed=31))
        finally:
            sched.close()
            engine.close()
        trace = tracer.chrome_trace()
        s = res.summary()
        h = engine.health()
        out[f"e{ee}"] = {
            "achieved_ops_per_sec": s.get("achieved_ops_per_sec"),
            "p99_commit_ms": s.get("p99_commit_ms"),
            "p50_commit_ms": s.get("p50_commit_ms"),
            "bubble_ratio_under_load":
                trace["otherData"]["bubble_ratio"],
            "rounds": trace["otherData"]["rounds_recorded_total"],
            "stash_overflow": h["stash_overflow"],
            "evict_buffer_occupancy": h.get("evict_buffer_occupancy"),
        }
    e1, e4 = out["e1"], out["e4"]
    if e1.get("achieved_ops_per_sec") and e4.get("achieved_ops_per_sec"):
        out["throughput_ratio_e4_over_e1"] = round(
            e4["achieved_ops_per_sec"] / e1["achieved_ops_per_sec"], 3)
    cap.emit("sharded_perf", **out)


def stage_cost_calibrate(cap, args):
    """Fit the cost observatory's achieved-bandwidth constant on real
    silicon and pre-rank the deferred ``auto`` knob decisions (PR 17).

    The static ledger (analysis/costmodel.py — cross-validated
    bit-exactly against the traced census by check_cost_model) prices
    a steady-state round in HBM bytes; this stage closes the loop with
    the one free parameter. A steady stream runs through the
    production scheduler with the full round observability attached
    (tracer + costmon — the same wiring a serving role gets), and the
    fit is

        achieved GB/s = modeled steady-round bytes / device span

    over the per-round host-observed device spans. The fitted constant
    is what operators export as ``GRAPEVINE_COST_GBPS``
    (obs/costmon.py resolution order) so the /metrics roofline
    residual reads ~1.0 on a healthy round instead of
    placeholder-shifted; residual spread (p10/p90 over the same spans)
    is banked so drift alerts can be sized to real round-to-round
    jitter. Alongside the fit, the model's verdict for every deferred
    ``auto`` knob is pre-ranked at BOTH scopes of the capture geometry
    — the record carries the predictions next to the measured stage
    results (sort_perf / tree_cache_perf / evict_perf /
    pipeline_perf) that grade them on-chip."""
    import jax
    import numpy as np

    from grapevine_tpu.analysis import costmodel as cm
    from grapevine_tpu.config import GrapevineConfig
    from grapevine_tpu.engine.batcher import GrapevineEngine
    from grapevine_tpu.load import (
        ScenarioRunner,
        calibrate_unloaded_round,
        steady_poisson,
    )
    from grapevine_tpu.obs import attach_round_observability
    from grapevine_tpu.server.scheduler import BatchScheduler

    cl, b, dur = (14, 16, 4.0) if args.quick else (18, 256, 10.0)
    cfg = GrapevineConfig(
        max_messages=1 << cl, max_recipients=1 << 10, batch_size=b,
    )
    engine = GrapevineEngine(cfg)
    tracer, _, _ = attach_round_observability(
        engine, engine.metrics.registry)
    _, est, _ = calibrate_unloaded_round(engine, 1_700_000_000)
    sched = BatchScheduler(engine, clock=lambda: 1_700_000_000)
    try:
        runner = ScenarioRunner(sched, n_idents=64,
                                settle_timeout_s=180.0)
        runner.run(steady_poisson(0.6 * est, dur, seed=37))
    finally:
        sched.close()
        engine.close()

    ledger = engine.costmon.ledger
    dev_ms = np.asarray(tracer.span_durations_ms("device"), dtype=float)
    dev_ms = dev_ms[dev_ms > 0.0]
    out = {
        "capacity_log2": cl, "batch": b,
        "backend": jax.default_backend(),
        "modeled_steady_round_bytes": int(ledger.steady_round_bytes),
        "rounds_fit": int(dev_ms.size),
        "placeholder_gbps": engine.costmon.bandwidth_gbps,
    }
    if dev_ms.size:
        med = float(np.median(dev_ms))
        fitted = ledger.steady_round_bytes / (med * 1e6)  # GB/s
        floor = ledger.floor_ms(fitted)
        out.update(
            fitted_gbps=round(fitted, 3),
            device_span_ms_p50=round(med, 3),
            floor_ms_at_fit=round(floor, 3),
            # spread of measured/floor at the fit — p50 is 1.0 by
            # construction; p10/p90 size the drift-alert band
            residual_p10=round(
                float(np.percentile(dev_ms, 10)) / med, 3),
            residual_p90=round(
                float(np.percentile(dev_ms, 90)) / med, 3),
        )
    # pre-ranked deferred auto-knob decisions at the capture geometry
    knobs = {}
    for kind in ("sort", "tree_cache", "evict", "pipeline"):
        per_scope = {}
        for scope in (("machinery", "sweep") if kind in
                      ("tree_cache", "evict", "sort") else ("machinery",)):
            v = cm.ab_verdict(kind, scope=scope, cap_n=1 << cl,
                              batch=b, backend=out["backend"])
            per_scope[scope] = {
                "winner": v["winner"],
                "arms": {a: d.get("modeled_bytes")
                         for a, d in v["arms"].items()},
            }
        knobs[kind] = per_scope
    out["auto_knob_rank"] = knobs
    cap.emit("cost_calibrate", **out)


STAGES = [
    ("probe", stage_probe, 420),
    ("headline", stage_headline, 1500),
    ("micro", stage_micro, 900),
    ("mosaic", stage_mosaic, 1200),
    # trace before pallas_perf: it reuses the headline's compiled
    # program (shared cache), so it is nearly free — and the first
    # window proved windows can close in minutes
    ("trace", stage_trace, 900),
    # live_profile right after trace: same geometry family, proves the
    # runtime /profile path and banks the device bubble ratio cheaply
    ("live_profile", stage_live_profile, 900),
    # load_perf next: reuses the live_profile geometry family's cached
    # compiles, and the knee + under-load bubble pair is the ROADMAP
    # item-2 decision input (more valuable than the remaining A/Bs if
    # the window closes here)
    ("load_perf", stage_load_perf, 1200),
    # pipeline_perf right after load_perf: same geometry family (cached
    # compiles) and the depth A/B + under-load bubble is the other half
    # of the ROADMAP-item-2 decision pair
    ("pipeline_perf", stage_pipeline_perf, 1200),
    # evict_perf right after pipeline_perf: same geometry family, and
    # the E A/B + flush-overlap bubble is the ROADMAP-item-1 decision
    # number that settles the evict_every auto (PR 15)
    ("evict_perf", stage_evict_perf, 1200),
    # sharded_perf right after evict_perf: the same E A/B replayed on
    # the device mesh (owner-masked flush; ISSUE 18) — the pair of
    # throughput ratios is the ROADMAP item-1 composition number
    ("sharded_perf", stage_sharded_perf, 1200),
    # cost_calibrate right after the decision stages it pre-ranks:
    # same geometry family (cached compiles), and the fitted
    # GRAPEVINE_COST_GBPS constant turns the /metrics roofline
    # residual from placeholder-shifted into ~1.0-on-healthy (PR 17)
    ("cost_calibrate", stage_cost_calibrate, 900),
    ("pallas_perf", stage_pallas_perf, 1800),
    ("vphases_perf", stage_vphases_perf, 1800),
    ("sort_perf", stage_sort_perf, 1800),
    ("posmap_perf", stage_posmap_perf, 1800),
    ("tree_cache_perf", stage_tree_cache_perf, 1800),
    ("oblivious", stage_oblivious, 900),
    ("fullbench", None, 2400),  # subprocess-only (see main loop)
]


def _last_parseable(stdout_text):
    """bench.py emits a full snapshot line after every config; take the
    LAST one that parses (a line cut mid-write must not sink the rest)."""
    for line in reversed((stdout_text or "").splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def _run_fullbench(cap, args):
    """Dress rehearsal of the driver's own artifact: run bench.py as a
    subprocess on the live backend and record its final JSON line. Also
    warms the shared XLA compilation cache, so the driver's end-of-round
    bench reuses every full-size program this run compiled."""
    if args.quick:
        # bench --smoke pins the CPU backend by design — a quick-pass
        # fullbench would record CPU numbers into a TPU artifact
        cap.emit("fullbench", skipped="quick mode (bench --smoke is CPU)")
        return 0
    cmd = [sys.executable, os.path.join(_REPO, "bench.py")]
    try:
        out = subprocess.run(cmd, capture_output=True, text=True,
                             cwd=_REPO, timeout=2300)
        rc, stdout = out.returncode, out.stdout
    except subprocess.TimeoutExpired as e:
        # salvage every completed-config snapshot bench already emitted
        rc, stdout = -1, (e.stdout.decode() if isinstance(e.stdout, bytes)
                          else e.stdout)
    parsed = _last_parseable(stdout)
    cap.emit("fullbench", rc=rc, parsed=parsed)
    return 0 if rc == 0 and parsed else 1


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--skip", default="")
    ap.add_argument("--out", default=os.path.join(_REPO, "TPURUN_r5.jsonl"))
    ap.add_argument("--stage", default="",
                    help="(internal) run ONE stage in this process")
    args = ap.parse_args()

    cap = Capture(args.out)

    if args.stage:  # child mode: one stage, in-process; parent owns timeout
        # share compiled programs across stage children where possible
        from grapevine_tpu.config import JAX_CACHE_DIR

        os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", JAX_CACHE_DIR)
        fn = dict((n, f) for n, f, _ in STAGES)[args.stage]
        try:
            if args.stage == "fullbench":
                return _run_fullbench(cap, args)
            fn(cap, args)
        except Exception as e:  # noqa: BLE001 — capture-everything harness
            cap.emit(args.stage, error=f"{type(e).__name__}: {e}")
            return 1
        return 0

    cap.emit("start", quick=args.quick, pid=os.getpid())
    skip = set(args.skip.split(",")) if args.skip else set()
    if args.quick:
        # bench --smoke pins the CPU backend by design — a quick-pass
        # fullbench would measure nothing; the full pass runs it
        skip.add("fullbench")
    failures = 0
    for name, _fn, cap_s in STAGES:
        if name in skip:
            cap.emit(name, skipped=True)
            continue
        cmd = [sys.executable, os.path.abspath(__file__),
               "--stage", name, "--out", args.out]
        if args.quick:
            cmd.append("--quick")
        wedged = False
        try:
            rc = subprocess.run(cmd, timeout=cap_s).returncode
        except subprocess.TimeoutExpired:
            cap.emit(name, error=f"stage killed after {cap_s}s "
                     "(wedged dispatch; child process terminated)")
            rc, wedged = -1, True
        if rc != 0:
            failures += 1
            if name == "probe":
                break  # no usable backend — nothing else can run
        if wedged:
            # A wedge usually means the relay died mid-window (window 1:
            # every stage after the first wedge also wedged, burning
            # 3x900s on a dead tunnel). Re-probe cheaply; if the relay
            # cannot answer a 256x256 matmul, the window is over.
            try:
                prc = subprocess.run(
                    [sys.executable, os.path.abspath(__file__),
                     "--stage", "probe", "--out", args.out],
                    timeout=180,
                ).returncode
            except subprocess.TimeoutExpired:
                prc = -1
            if prc != 0:
                cap.emit("abort", reason=f"window closed (re-probe failed "
                         f"after {name} wedged)")
                break
    cap.emit("done", failures=failures)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
