#!/usr/bin/env python
"""Telemetry leak-policy checker (CI gate; invoked by a tier-1 test).

Two passes, mirroring how testing/leakcheck.py checks the transcript:

1. **Static scan** — grep every instrumentation call site under
   ``grapevine_tpu/`` for forbidden label keys (per-client / per-op
   dimensions). A kwarg like ``op_type=`` on a ``labels()``/``inc()``/
   ``observe()`` call, or a forbidden key inside a ``labels={...}``
   registration, fails the check with file:line — before the code ever
   runs.
2. **Registry audit** — instantiate the shipped registry (the one
   ``EngineMetrics`` builds, i.e. exactly what /metrics exports) and run
   ``TelemetryRegistry.audit()``: every label key must be allowlisted,
   every series declared, every histogram's buckets fixed.

Exit 0 = policy holds; exit 1 = a violation, printed with its location.

Run directly::

    python tools/check_telemetry_policy.py
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "grapevine_tpu")

#: must match obs.registry.FORBIDDEN_LABEL_KEYS (imported below for the
#: audit pass; duplicated here only to build the static regex without
#: importing before the scan)
_FORBIDDEN = (
    "client", "client_id", "session", "session_id", "channel",
    "channel_id", "user", "user_id", "identity", "auth", "auth_identity",
    "msg_id", "message_id", "sender", "recipient", "key", "block",
    "leaf", "path", "op", "op_type", "operation", "request_type",
)

#: telemetry call sites: sample calls with label kwargs, and
#: registration calls with a labels= declaration
_CALL_RE = re.compile(
    r"\.(?:labels|inc|observe|set|set_max|counter|gauge|histogram)\("
)
_KWARG_RES = [
    (k, re.compile(rf"[(,]\s*{k}\s*=")) for k in _FORBIDDEN
]
_DECL_RES = [
    (k, re.compile(rf"""labels\s*=\s*\{{[^}}]*['"]{k}['"]""")) for k in _FORBIDDEN
]


def _call_site_spans(text: str):
    """Yield (lineno, span_text) for each telemetry call, where span_text
    covers the call through its closing paren (label kwargs may sit on
    continuation lines)."""
    for m in _CALL_RE.finditer(text):
        start = m.end() - 1  # the opening paren
        depth = 0
        end = start
        for i in range(start, min(len(text), start + 2000)):
            c = text[i]
            if c == "(":
                depth += 1
            elif c == ")":
                depth -= 1
                if depth == 0:
                    end = i + 1
                    break
        yield text.count("\n", 0, m.start()) + 1, text[m.start():end]


def scan_call_sites() -> list[str]:
    """Static pass: forbidden label keys at instrumentation call sites."""
    violations = []
    for dirpath, _, names in os.walk(PKG):
        for name in sorted(names):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, REPO)
            with open(path, encoding="utf-8") as fh:
                text = fh.read()
            for lineno, span in _call_site_spans(text):
                for key, rx in _KWARG_RES:
                    if rx.search(span):
                        violations.append(
                            f"{rel}:{lineno}: telemetry call passes "
                            f"forbidden label key {key!r}"
                        )
            for key, rx in _DECL_RES:
                for m in rx.finditer(text):
                    lineno = text.count("\n", 0, m.start()) + 1
                    violations.append(
                        f"{rel}:{lineno}: metric registration declares "
                        f"forbidden label key {key!r}"
                    )
    return violations


def audit_shipped_registry() -> dict:
    """Runtime pass: the registry EngineMetrics ships must pass audit."""
    sys.path.insert(0, REPO)
    from grapevine_tpu.engine.metrics import EngineMetrics
    from grapevine_tpu.obs.registry import FORBIDDEN_LABEL_KEYS

    missing = set(_FORBIDDEN) - set(FORBIDDEN_LABEL_KEYS)
    if missing:
        raise SystemExit(
            f"checker's forbidden-key list drifted from obs.registry: "
            f"{sorted(missing)} not in FORBIDDEN_LABEL_KEYS"
        )
    return EngineMetrics().registry.audit()


def audit_leakmon_registry() -> dict:
    """Runtime pass over the leak monitor's metric namespace.

    Builds the registry exactly as a --leakmon engine does (EngineMetrics
    + EngineLeakMonitor on the same registry) and asserts, beyond the
    generic ``audit()``:

    - the ``grapevine_leakmon_*`` families exist (the continuous audit
      is actually exporting, not silently unregistered);
    - their only label key is ``tree`` with the declared tree names —
      aggregate-only by construction, never per-client/per-op;
    - any histogram in the namespace has registration-fixed buckets
      (audit() re-checks the boundaries object-level).
    """
    sys.path.insert(0, REPO)
    from grapevine_tpu.engine.metrics import EngineMetrics
    from grapevine_tpu.obs.flightrec import FlightRecorder
    from grapevine_tpu.obs.leakmon import EngineLeakMonitor

    em = EngineMetrics()
    mon = EngineLeakMonitor(
        mb_leaves=1 << 4, rec_leaves=1 << 7, mb_choices=2,
        registry=em.registry, recorder=FlightRecorder(capacity=8),
    )
    try:
        report = em.registry.audit()  # raises on any violation
        families = [
            m for m in em.registry.collect()
            if m.name.startswith("grapevine_leakmon_")
        ]
        if not families:
            raise SystemExit(
                "leakmon namespace missing: EngineLeakMonitor registered "
                "no grapevine_leakmon_* metrics"
            )
        for m in families:
            bad = set(m.label_keys) - {"tree"}
            if bad:
                raise SystemExit(
                    f"leakmon metric {m.name!r} carries label keys "
                    f"{sorted(bad)} — the continuous audit may only "
                    "aggregate by tree"
                )
        report["leakmon_families"] = len(families)
        return report
    finally:
        mon.close()


def audit_trace_slo_registry() -> dict:
    """Runtime pass over the round tracer's and SLO tracker's metric
    namespaces plus the tracer ring schema (ISSUE-6 satellite — the
    same TelemetryLeakError contract as the flight recorder):

    - the ``grapevine_trace_*`` / ``grapevine_slo_*`` families and the
      derived ``grapevine_round_bubble_ratio`` gauge exist and carry NO
      label keys (batch-level scalars only, no dimension to hide an
      identity in);
    - the tracer's span-name allowlist is exactly phases + derived
      windows — nothing outside the canonical PHASES vocabulary;
    - ``record_round`` rejects a per-op span name and a non-(start,dur)
      span value with TelemetryLeakError (enforcement has teeth, not
      just a clean default).
    """
    sys.path.insert(0, REPO)
    from grapevine_tpu.engine.metrics import EngineMetrics
    from grapevine_tpu.obs.phases import PHASES
    from grapevine_tpu.obs.registry import TelemetryLeakError
    from grapevine_tpu.obs.slo import SloTracker
    from grapevine_tpu.obs.tracer import ALLOWED_SPAN_NAMES, RoundTracer

    em = EngineMetrics()
    tracer = RoundTracer(capacity=8, registry=em.registry)
    SloTracker(registry=em.registry)
    report = em.registry.audit()  # raises on any violation

    families = [
        m for m in em.registry.collect()
        if m.name.startswith(("grapevine_trace_", "grapevine_slo_"))
        or m.name == "grapevine_round_bubble_ratio"
    ]
    if len(families) < 3:
        raise SystemExit(
            "trace/slo namespace missing: RoundTracer/SloTracker "
            f"registered only {[m.name for m in families]}"
        )
    for m in families:
        if m.label_keys:
            raise SystemExit(
                f"trace/slo metric {m.name!r} carries label keys "
                f"{list(m.label_keys)} — these series are batch-level "
                "scalars with no dimensions by design"
            )

    stray = ALLOWED_SPAN_NAMES - set(PHASES) - {"device", "round"}
    if stray:
        raise SystemExit(
            f"tracer span allowlist drifted outside the phase "
            f"vocabulary: {sorted(stray)}"
        )
    for bad_ledger, why in (
        ({"op_read": (0.0, 1.0)}, "per-op span name"),
        ({"evict": "not-a-span"}, "non-(start,dur) span value"),
        ({"evict": (0.0, -1.0)}, "negative duration"),
    ):
        try:
            tracer.record_round(bad_ledger)
        except TelemetryLeakError:
            continue
        raise SystemExit(
            f"tracer ring schema has no teeth: {why} was accepted"
        )
    report["trace_slo_families"] = len(families)
    return report


def audit_workload_registry() -> dict:
    """Runtime pass over the workload observatory's metric namespace
    (ISSUE-9 satellite — the ``grapevine_load_*`` families plus the
    flight recorder's queue-depth summary field):

    - the fill/depth histograms, arrival counter/gauge, utilization
      gauge, and saturation/backpressure counters exist; the ONLY
      label key anywhere in the namespace is ``phase`` (on the
      utilization gauge, with registration-declared values) — no
      dimension in which a client, key, or op type could travel;
    - histogram buckets are the registration-time FILL/DEPTH constants
      (fixed-bucket contract; a data-dependent layout is a signal);
    - schema teeth: the flight recorder accepts a scalar
      ``queue_depth`` and rejects an array-valued one with
      TelemetryLeakError (an array is how per-op data would ride a
      batch-level field).
    """
    sys.path.insert(0, REPO)
    from grapevine_tpu.engine.metrics import EngineMetrics
    from grapevine_tpu.obs.flightrec import FlightRecorder
    from grapevine_tpu.obs.registry import TelemetryLeakError
    from grapevine_tpu.obs.workload import (
        DEPTH_BUCKETS,
        FILL_BUCKETS,
        WorkloadTelemetry,
    )

    em = EngineMetrics()
    WorkloadTelemetry(em.registry, batch_size=256)
    report = em.registry.audit()  # raises on any violation

    families = [
        m for m in em.registry.collect()
        if m.name.startswith("grapevine_load_")
    ]
    if len(families) < 6:
        raise SystemExit(
            "workload namespace missing: WorkloadTelemetry registered "
            f"only {[m.name for m in families]}"
        )
    for m in families:
        bad = set(m.label_keys) - {"phase"}
        if bad:
            raise SystemExit(
                f"workload metric {m.name!r} carries label keys "
                f"{sorted(bad)} — workload telemetry may only "
                "aggregate by phase"
            )
    fill = em.registry.get("grapevine_load_batch_fill")
    depth = em.registry.get("grapevine_load_queue_depth")
    if fill is None or fill.buckets != tuple(FILL_BUCKETS):
        raise SystemExit("fill histogram buckets drifted from the "
                         "registration-time constants")
    if depth is None or depth.buckets != tuple(DEPTH_BUCKETS):
        raise SystemExit("depth histogram buckets drifted from the "
                         "registration-time constants")

    fr = FlightRecorder(capacity=2)
    fr.record({"seq": 1, "fill": 0.5, "queue_depth": 17})  # scalar: fine
    try:
        fr.record({"seq": 2, "queue_depth": [1, 2, 3]})
    except TelemetryLeakError:
        pass
    else:
        raise SystemExit(
            "flight recorder accepted an array-valued queue_depth — "
            "the batch-level schema has no teeth"
        )
    report["workload_families"] = len(families)
    return report


def audit_evict_registry() -> dict:
    """Runtime pass over the delayed-eviction observability surface
    (ISSUE-15 satellite — the eviction-buffer occupancy stream plus the
    ``flush`` phase):

    - the ``grapevine_evict_buffer_occupancy`` / ``_high_water``
      gauges exist and carry NO label keys — the canary is a per-tree
      SUM at scrape cadence; any dimension (tree, client, key) would
      be a finer-grained channel than the reviewed policy admits;
    - ``flush`` is in the canonical PHASES vocabulary, so the phase
      histogram, the tracer span allowlist, and the flight recorder's
      ``phase_s`` schema all admit it (one vocabulary, three surfaces);
    - schema teeth: the phase histogram accepts ``flush`` and rejects
      a per-window variant (``flush_w3``) with TelemetryLeakError —
      a window-numbered phase name is how a schedule-position channel
      would ride the declared-values contract.
    """
    sys.path.insert(0, REPO)
    from grapevine_tpu.engine.metrics import EngineMetrics
    from grapevine_tpu.obs.flightrec import ALLOWED_PHASE_KEYS
    from grapevine_tpu.obs.phases import PHASES
    from grapevine_tpu.obs.registry import TelemetryLeakError

    if "flush" not in PHASES:
        raise SystemExit(
            "'flush' missing from obs.phases.PHASES — the delayed-"
            "eviction dispatch would time under an undeclared name"
        )
    if "flush" not in ALLOWED_PHASE_KEYS:
        raise SystemExit(
            "'flush' missing from the flight recorder's phase schema"
        )
    em = EngineMetrics()
    report = em.registry.audit()  # raises on any violation
    for name in ("grapevine_evict_buffer_occupancy",
                 "grapevine_evict_buffer_high_water"):
        m = em.registry.get(name)
        if m is None:
            raise SystemExit(
                f"eviction canary {name!r} not registered — the "
                "overflow runbook (OPERATIONS.md §19) has no signal"
            )
        if m.label_keys:
            raise SystemExit(
                f"eviction canary {name!r} carries label keys "
                f"{sorted(m.label_keys)} — the occupancy stream is a "
                "label-free scrape-cadence sum by policy"
            )
    em.observe_phase("flush", 0.001)  # declared value: fine
    try:
        em.observe_phase("flush_w3", 0.001)
    except TelemetryLeakError:
        pass
    else:
        raise SystemExit(
            "phase histogram accepted the window-numbered phase "
            "'flush_w3' — the declared-values contract has no teeth"
        )
    return report


def audit_fleet_registry() -> dict:
    """Runtime pass over the fleet observatory's metric namespace
    (ISSUE-16 satellite — the ``grapevine_fleet_*`` families the
    aggregator and the cross-shard uniformity monitor register):

    - ``shard`` is the ONLY label key anywhere in the namespace, and
      every declared value is a bare integer index (position in the
      declared member list — public topology; a member NAME or
      ADDRESS in a label value would export deployment identity);
    - the uniformity detectors export statistic/threshold/verdict
      scalars only — label-free pairs per detector, no per-shard
      payload-derived fields (the per-shard series the detectors
      consume stay inside the monitor);
    - teeth: registering a member-name or address label value under
      ``shard``, or a ``member`` label key, raises TelemetryLeakError
      at registration — the integer-index rule is enforcement, not
      convention.
    """
    sys.path.insert(0, REPO)
    from grapevine_tpu.obs.fleet import FleetAggregator, FleetConfig
    from grapevine_tpu.obs.registry import (
        TelemetryLeakError,
        TelemetryRegistry,
    )

    agg = FleetAggregator(FleetConfig(members=("h0:1", "h1:1", "h2:1")))
    report = agg.registry.audit()  # raises on any violation

    families = [
        m for m in agg.registry.collect()
        if m.name.startswith("grapevine_fleet_")
    ]
    if len(families) < 8:
        raise SystemExit(
            "fleet namespace missing: aggregator registered only "
            f"{[m.name for m in families]}"
        )
    for m in families:
        bad = set(m.label_keys) - {"shard"}
        if bad:
            raise SystemExit(
                f"fleet metric {m.name!r} carries label keys "
                f"{sorted(bad)} — 'shard' is the only permitted key "
                "in the grapevine_fleet_* namespace"
            )
        for v in m.labels_decl.get("shard", ()):
            if not (v.isascii() and v.isdigit()):
                raise SystemExit(
                    f"fleet metric {m.name!r} declares shard value "
                    f"{v!r} — values must be bare integer indices"
                )
    # the uniformity detector exports: statistic/threshold pairs per
    # detector plus the verdict gauge, all label-free scalars
    for det in ("cadence_ratio", "fill_load_correlation", "flush_phase"):
        for kind in ("statistic", "threshold"):
            name = f"grapevine_fleet_uniformity_{det}_{kind}"
            m = agg.registry.get(name)
            if m is None:
                raise SystemExit(f"uniformity export {name!r} missing")
            if m.label_keys:
                raise SystemExit(
                    f"uniformity export {name!r} carries label keys "
                    f"{list(m.label_keys)} — detector exports are "
                    "label-free scalars by policy"
                )
    if agg.registry.get("grapevine_fleet_uniformity_suspect") is None:
        raise SystemExit("uniformity verdict gauge missing")

    # teeth: member identity can never ride a label
    r = TelemetryRegistry()
    for labels, why in (
        ({"shard": ("engine-a.internal",)}, "member-name shard value"),
        ({"shard": ("10.0.0.7:9464",)}, "address shard value"),
        ({"member": ("0",)}, "'member' label key"),
    ):
        try:
            r.gauge("grapevine_fleet_teeth_probe", "probe", labels=labels)
        except TelemetryLeakError:
            continue
        raise SystemExit(
            f"fleet label policy has no teeth: {why} was accepted at "
            "registration"
        )
    report["fleet_families"] = len(families)
    return report


def audit_cost_registry() -> dict:
    """Runtime pass over the cost observatory's metric namespace.

    Builds the registry exactly as ``attach_round_observability`` does
    (a CostMonitor over a real EngineConfig) and asserts, beyond the
    generic ``audit()``:

    - the ``grapevine_cost_*`` families exist (the ledger is actually
      exporting: per-phase bytes/rows/cipher/sort, the steady-state
      total, the calibrated bandwidth, the roofline floor + residual);
    - ``phase`` is the only label key in the namespace, and its
      declared values are exactly the model's fixed schedule names
      (:data:`costmodel.COST_PHASES`) — public program structure.
      Geometry belongs in gauge VALUES (which any observer could
      derive from the config), never in label sets;
    - teeth: a geometry-shaped label key (``capacity``/``geometry``)
      or a geometry value smuggled into ``phase`` raises
      TelemetryLeakError at registration — the allowlist plus the
      fixed-phase rule are enforcement, not convention.
    """
    sys.path.insert(0, REPO)
    from grapevine_tpu.analysis.costmodel import COST_PHASES
    from grapevine_tpu.config import GrapevineConfig
    from grapevine_tpu.engine.state import EngineConfig
    from grapevine_tpu.obs.costmon import CostMonitor
    from grapevine_tpu.obs.registry import (
        TelemetryLeakError,
        TelemetryRegistry,
    )

    reg = TelemetryRegistry()
    ecfg = EngineConfig.from_config(GrapevineConfig(
        max_messages=1 << 10, max_recipients=1 << 7, batch_size=8,
    ))
    CostMonitor(ecfg, reg, bandwidth_gbps=8.0)
    report = reg.audit()  # raises on any violation

    families = [
        m for m in reg.collect() if m.name.startswith("grapevine_cost_")
    ]
    if len(families) < 9:
        raise SystemExit(
            "cost namespace missing: CostMonitor registered only "
            f"{[m.name for m in families]}"
        )
    for m in families:
        bad = set(m.label_keys) - {"phase"}
        if bad:
            raise SystemExit(
                f"cost metric {m.name!r} carries label keys "
                f"{sorted(bad)} — 'phase' is the only permitted key "
                "in the grapevine_cost_* namespace"
            )
        for v in m.labels_decl.get("phase", ()):
            if v not in COST_PHASES:
                raise SystemExit(
                    f"cost metric {m.name!r} declares phase value "
                    f"{v!r} — values must be the fixed schedule names "
                    f"{COST_PHASES}, never geometry"
                )
    for name in ("grapevine_cost_roofline_residual",
                 "grapevine_cost_roofline_floor_ms",
                 "grapevine_cost_steady_round_hbm_bytes"):
        m = reg.get(name)
        if m is None:
            raise SystemExit(f"cost export {name!r} missing")
        if m.label_keys:
            raise SystemExit(
                f"cost export {name!r} carries label keys "
                f"{list(m.label_keys)} — roofline exports are "
                "label-free scalars by policy"
            )

    # teeth: geometry can never ride a label in this namespace
    r = TelemetryRegistry()
    for labels, why in (
        ({"capacity": ("65536",)}, "geometry-value 'capacity' label key"),
        ({"geometry": ("h14_z4",)}, "'geometry' label key"),
        ({"leaf": ("12",)}, "'leaf' label key"),
    ):
        try:
            r.gauge("grapevine_cost_teeth_probe", "probe", labels=labels)
        except TelemetryLeakError:
            continue
        raise SystemExit(
            f"cost label policy has no teeth: {why} was accepted at "
            "registration"
        )
    report["cost_families"] = len(families)
    return report


def audit_host_registry() -> dict:
    """Runtime pass over the host serving pipeline's metric namespace
    (ISSUE-20 satellite — the ``grapevine_host_*`` families from the
    multiprocess verify/codec pool, the SLO-adaptive window policy, and
    the flush-aware collection stretch):

    - builds the registry exactly as the serving layer does — a real
      ``HostPipeline`` (worker processes spawned, then closed), a real
      ``AdaptiveBatchPolicy``, and a flush-windowed ``BatchScheduler``
      all registering into one merged registry, as /metrics serves it;
    - the ONLY label keys anywhere in the namespace are ``phase``
      (declared task kinds / decision kinds — fixed vocabularies) and
      ``worker`` (pool indices declared at registration from the
      --host-workers config: public topology, never identity);
    - ``worker`` values are exactly the configured pool's digit
      strings — many channels hash onto one worker and the mapping is
      never exported, so the index reveals pool size only;
    - teeth: a channel-id-shaped ``worker`` value, a non-digit worker
      name, and a ``channel_id`` label key each raise
      TelemetryLeakError at registration — the sticky-routing design
      (sessions pinned to workers by channel hash) is precisely where
      a per-channel dimension would be tempting, so the rule is
      enforcement, not convention.
    """
    sys.path.insert(0, REPO)
    from grapevine_tpu.server.adaptive import (
        DECISION_KINDS,
        AdaptiveBatchPolicy,
    )
    from grapevine_tpu.server.hostpipe import TASK_KINDS, HostPipeline
    from grapevine_tpu.server.scheduler import BatchScheduler
    from grapevine_tpu.obs.registry import (
        TelemetryLeakError,
        TelemetryRegistry,
    )

    reg = TelemetryRegistry()
    pipe = HostPipeline(workers=2, registry=reg)
    sched = None
    try:
        AdaptiveBatchPolicy(8, 0.008, 0.002, registry=reg)

        class _Ecfg:
            batch_size = 8

        class _Metrics:
            registry = reg

        class _Engine:
            ecfg = _Ecfg()
            metrics = _Metrics()

        sched = BatchScheduler(_Engine(), flush_window_ms=4.0)
    finally:
        if sched is not None:
            sched.close()
        pipe.close()
    report = reg.audit()  # raises on any violation

    families = [
        m for m in reg.collect() if m.name.startswith("grapevine_host_")
    ]
    if len(families) < 9:
        raise SystemExit(
            "host namespace missing: serving layer registered only "
            f"{[m.name for m in families]}"
        )
    for m in families:
        bad = set(m.label_keys) - {"phase", "worker"}
        if bad:
            raise SystemExit(
                f"host metric {m.name!r} carries label keys "
                f"{sorted(bad)} — 'phase' and 'worker' are the only "
                "permitted keys in the grapevine_host_* namespace"
            )
        for v in m.labels_decl.get("worker", ()):
            if not v.isdigit():
                raise SystemExit(
                    f"host metric {m.name!r} declares worker value "
                    f"{v!r} — worker values must be pool indices "
                    "(digit strings), never names or identities"
                )
    tasks = reg.get("grapevine_host_tasks_total")
    if tasks is None or tuple(tasks.labels_decl["worker"]) != ("0", "1"):
        raise SystemExit(
            "grapevine_host_tasks_total worker values drifted from the "
            "configured pool indices"
        )
    for v in tasks.labels_decl["phase"]:
        if v not in TASK_KINDS:
            raise SystemExit(
                f"grapevine_host_tasks_total declares phase {v!r} — "
                f"values must be the fixed task kinds {TASK_KINDS}"
            )
    dec = reg.get("grapevine_host_adaptive_decisions_total")
    if dec is None:
        raise SystemExit("adaptive decision counter missing")
    for v in dec.labels_decl["phase"]:
        if v not in DECISION_KINDS:
            raise SystemExit(
                f"adaptive decision counter declares phase {v!r} — "
                f"values must be the fixed decision kinds "
                f"{DECISION_KINDS}"
            )

    # teeth: a channel identity can never ride the worker dimension
    r = TelemetryRegistry()
    for labels, why in (
        ({"worker": ("deadbeef" * 4,)}, "channel-id-shaped worker value"),
        ({"worker": ("w0",)}, "non-digit worker value"),
        ({"channel_id": ("0",)}, "'channel_id' label key"),
    ):
        try:
            r.counter("grapevine_host_teeth_probe", "probe", labels=labels)
        except TelemetryLeakError:
            continue
        raise SystemExit(
            f"host label policy has no teeth: {why} was accepted at "
            "registration"
        )
    report["host_families"] = len(families)
    return report


def main() -> int:
    violations = scan_call_sites()
    for v in violations:
        print(f"TELEMETRY POLICY VIOLATION: {v}", file=sys.stderr)
    report = audit_shipped_registry()
    lm_report = audit_leakmon_registry()
    ts_report = audit_trace_slo_registry()
    wl_report = audit_workload_registry()
    audit_evict_registry()
    fl_report = audit_fleet_registry()
    cost_report = audit_cost_registry()
    host_report = audit_host_registry()
    print(
        f"telemetry policy: static scan "
        f"{'FAILED' if violations else 'clean'}; registry audit ok "
        f"({report['metrics']} metrics, {report['series']} series); "
        f"leakmon audit ok ({lm_report['leakmon_families']} families, "
        f"{lm_report['series']} series incl. engine); trace/slo audit "
        f"ok ({ts_report['trace_slo_families']} families, ring schema "
        f"enforced); workload audit ok ({wl_report['workload_families']} "
        "families, fixed buckets, depth-field teeth); evict audit ok "
        "(label-free buffer canaries, flush phase declared, teeth); "
        f"fleet audit ok ({fl_report['fleet_families']} families, "
        "shard-only integer labels, teeth); cost audit ok "
        f"({cost_report['cost_families']} families, phase-only labels, "
        "fixed schedule values, teeth); host audit ok "
        f"({host_report['host_families']} families, phase/worker-only "
        "labels, digit worker indices, teeth)"
    )
    return 1 if violations else 0


if __name__ == "__main__":
    raise SystemExit(main())
