"""Cost-model gate: the two-derivation ledger identity, its mutant
teeth, and the model-graded knob decisions.

Three checks, the PR-12/14 analyzer discipline applied to the cost
observatory (grapevine_tpu/analysis/costmodel.py, obs/costmon.py):

1. **Ledger ↔ census identity** (``--smoke``, the tier-1 slice): the
   analytic row model — a pure function of geometry × knobs — must
   agree **bit-exactly per operand shape class** with the traced
   census accounting (the shared ``jaxpr_walk`` reduction) across the
   shipped knob matrix: cache-k × posmap × evict_every for
   ``oram_round``/``oram_flush``, the composed engine round at E=1 and
   E=2 (the fetch/flush split), the engine flush, and the expiry
   sweep's chunked scan. Trace-only — zero engine compiles.
2. **Mutant teeth**: every seeded undercount mutant (a dropped plane, a
   halved fetch, a forgotten second nonce gather, a missed mailbox
   double-round, …) must trip ``CostModelMismatch``, reported through
   the shared ``mutants.control_failures`` runner — a checker that
   cannot catch a planted defect is vacuous.
3. **Trajectory grading** (``--grade``): replay every banked
   BENCH_trajectory.jsonl A/B line (sort_ab / tree_cache_ab /
   evict_ab / sharded_evict_ab / pipeline_ab, machinery and sweep
   scopes) and report the
   modeled winner next to the measured winner. Agreement is REPORTED
   per config — a disagreement is a finding about the model (or a
   machine regime the bytes model does not price), printed loudly, not
   a gate failure; missing coverage of a banked A/B kind IS a failure.

Standalone: ``python tools/check_cost_model.py [--smoke] [--grade]
[--trajectory PATH] [--skip-mutants] [-v]`` (no flags = smoke + grade).
Tier-1 wiring: tests/test_cost_model.py runs the smoke slice in-process.
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from grapevine_tpu.analysis import costmodel as cm  # noqa: E402
from grapevine_tpu.analysis.mutants import control_failures  # noqa: E402

TRAJECTORY = os.path.join(REPO, "BENCH_trajectory.jsonl")


# -- check 1: the two-derivation identity over the shipped matrix -------


def run_identity_matrix(verbose: bool = False) -> list:
    """Cross-validate analytic vs traced rows across the shipped
    trace-only knob matrix. Returns problem strings (empty = pass)."""
    problems = []

    def _run(label, fn, *a, **kw):
        try:
            fn(*a, **kw)
            if verbose:
                print(f"[check_cost_model]   ok {label}")
        except cm.CostModelMismatch as m:
            problems.append(f"{label}: {m}")

    for name, cfg, b in cm.audit_oram_configs():
        _run(f"round/{name}", cm.cross_validate_round, cfg, b)
        if cfg.delayed_eviction:
            _run(f"flush/{name}", cm.cross_validate_flush, cfg)
    for name, ecfg in cm.audit_engine_configs():
        _run(f"{name}/round", cm.cross_validate_engine_round, ecfg)
        if ecfg.evict_every > 1:
            _run(f"{name}/flush", cm.cross_validate_engine_flush, ecfg)
        _run(f"{name}/sweep", cm.cross_validate_sweep, ecfg)
    # the owner-masked sharded flush (ISSUE 18): shard-local analytic
    # rows vs the shard_map-traced census, on whatever mesh slice the
    # process actually has (main() forces >=2 virtual CPU devices when
    # it owns the jax init)
    for name, cfg, shards in cm.audit_sharded_flush_configs():
        _run(f"{name}/s{shards}", cm.cross_validate_sharded_flush,
             cfg, shards)
    return problems


# -- check 2: mutant teeth ---------------------------------------------


def run_cost_mutant_controls(log=print) -> list:
    return control_failures(cm.run_cost_mutants(), "cost-model mutant",
                            log=log)


# -- check 3: grade the model against the banked trajectory ------------


def _measured_winner(arms: dict, key: str, lower_is_better=True):
    """Winner among arm sub-dicts carrying metric ``key``."""
    scored = {a: d[key] for a, d in arms.items()
              if isinstance(d, dict) and key in d}
    if not scored:
        return None
    pick = min if lower_is_better else max
    return pick(scored, key=scored.get)


def _grade_entry(results, kind, config_id, modeled, measured, basis=""):
    agree = (modeled == measured) if measured else None
    results.append({
        "kind": kind, "config": config_id, "modeled": modeled,
        "measured": measured, "agree": agree, "basis": basis,
    })


def _parse_cap_b(group_name: str):
    """'round_cap65536_b256' -> (65536, 256)."""
    cap = int(group_name.split("cap")[1].split("_")[0])
    b = int(group_name.split("_b")[1])
    return cap, b


def _parse_cap_b_s(group_name: str):
    """'round_cap4096_b64_s2' -> (4096, 64, 2) — the sharded_evict_ab
    group key (geometry: capacity x batch x mesh width)."""
    cap = int(group_name.split("cap")[1].split("_")[0])
    rest = group_name.split("_b")[1]
    b, s = rest.split("_s")
    return cap, int(b), int(s)


def grade_trajectory(path: str = TRAJECTORY) -> tuple:
    """Grade the model against every banked A/B line.

    Returns ``(results, problems)``: one result row per banked config
    (modeled vs measured winner), problems for parse/coverage gaps."""
    results: list = []
    problems: list = []
    kinds_seen = set()
    if not os.path.exists(path):
        return results, [f"trajectory file missing: {path}"]
    with open(path) as fh:
        lines = [json.loads(ln) for ln in fh if ln.strip()]

    for line in lines:
        pr = line.get("pr", "?")
        backend = line.get("backend", "cpu")
        configs = line.get("configs", {})

        if "sort_ab" in configs:
            kinds_seen.add("sort")
            v = cm.ab_verdict("sort", backend=backend)
            for scope in ("machinery", "sweep"):
                for gname, arms in configs["sort_ab"].get(scope, {}).items():
                    sp = arms.get("speedup_radix_over_xla")
                    if sp is None:
                        continue
                    measured = "radix" if sp > 1.0 else "xla"
                    _grade_entry(results, "sort",
                                 f"{pr}/{scope}/{gname}",
                                 v["winner"], measured, v["basis"])

        if "tree_cache_ab" in configs:
            kinds_seen.add("tree_cache")
            ab = configs["tree_cache_ab"]
            for gname, arms in ab.get("machinery", {}).items():
                cap, b = _parse_cap_b(gname)
                ks = sorted(int(a[1:]) for a in arms if a[1:].isdigit())
                v = cm.ab_verdict("tree_cache", scope="machinery",
                                  cap_n=cap, batch=b, arms=ks)
                measured = _measured_winner(arms, "round_ms")
                _grade_entry(results, "tree_cache",
                             f"{pr}/machinery/{gname}",
                             v["winner"], measured, v["basis"])
            for bstr, arms in ab.get("sweep", {}).items():
                numeric = {a: d for a, d in arms.items()
                           if a[1:].isdigit()}
                ks = sorted(int(a[1:]) for a in numeric)
                v = cm.ab_verdict("tree_cache", scope="sweep",
                                  batch=int(bstr), arms=ks)
                measured = _measured_winner(numeric, "round_ms")
                _grade_entry(results, "tree_cache",
                             f"{pr}/sweep/b{bstr}",
                             v["winner"], measured, v["basis"])

        if "evict_ab" in configs:
            kinds_seen.add("evict")
            ab = configs["evict_ab"]
            for gname, arms in ab.get("machinery", {}).items():
                cap, b = _parse_cap_b(gname)
                es = sorted(int(a[1:]) for a in arms if a[1:].isdigit())
                v = cm.ab_verdict("evict", scope="machinery",
                                  cap_n=cap, batch=b, arms=es)
                measured = _measured_winner(arms, "amortized_round_ms")
                _grade_entry(results, "evict",
                             f"{pr}/machinery/{gname}",
                             v["winner"], measured, v["basis"])
            for bstr, arms in ab.get("sweep", {}).items():
                es = sorted(int(a[1:]) for a in arms if a[1:].isdigit())
                v = cm.ab_verdict("evict", scope="sweep",
                                  batch=int(bstr), arms=es)
                measured = _measured_winner(arms, "amortized_round_ms")
                _grade_entry(results, "evict",
                             f"{pr}/sweep/b{bstr}",
                             v["winner"], measured, v["basis"])

        if "sharded_evict_ab" in configs:
            kinds_seen.add("sharded_evict")
            ab = configs["sharded_evict_ab"]
            for gname, arms in ab.get("machinery", {}).items():
                cap, b, s = _parse_cap_b_s(gname)
                es = sorted(int(a[1:]) for a in arms if a[1:].isdigit())
                v = cm.ab_verdict("sharded_evict", scope="machinery",
                                  cap_n=cap, batch=b, arms=es, shards=s)
                measured = _measured_winner(arms, "amortized_round_ms")
                _grade_entry(results, "sharded_evict",
                             f"{pr}/machinery/{gname}",
                             v["winner"], measured, v["basis"])

        if "pipeline_ab" in configs:
            kinds_seen.add("pipeline")
            ab = configs["pipeline_ab"]
            v = cm.ab_verdict("pipeline")
            measured = _measured_winner(
                {a: ab[a] for a in ("depth1", "depth2") if a in ab},
                "ops_per_sec", lower_is_better=False)
            _grade_entry(results, "pipeline", f"{pr}/pipeline_ab",
                         v["winner"], measured, v["basis"])

    for kind in ("sort", "tree_cache", "evict", "pipeline",
                 "sharded_evict"):
        if kind not in kinds_seen:
            problems.append(
                f"banked trajectory has no {kind}_ab line to grade — "
                "every banked A/B config must get a modeled verdict"
            )
    return results, problems


def print_grade_report(results) -> tuple:
    agree = sum(1 for r in results if r["agree"])
    total = sum(1 for r in results if r["agree"] is not None)
    for r in results:
        mark = ("AGREE" if r["agree"]
                else "DISAGREE" if r["agree"] is not None else "n/a")
        print(f"[check_cost_model]   {r['kind']:11s} "
              f"{r['config']:42s} model={r['modeled']:6s} "
              f"measured={str(r['measured']):6s} {mark}")
    print(f"[check_cost_model] model-vs-measured winner agreement: "
          f"{agree}/{total} banked configs")
    return agree, total


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="identity matrix + mutants only (tier-1)")
    ap.add_argument("--grade", action="store_true",
                    help="grade the model against the banked "
                         "trajectory only")
    ap.add_argument("--trajectory", default=TRAJECTORY)
    ap.add_argument("--skip-mutants", action="store_true")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)
    do_smoke = args.smoke or not args.grade
    do_grade = args.grade or not args.smoke

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # the sharded-flush audit wants a real (if virtual) mesh slice; the
    # flag only takes effect if jax has not initialized its backend yet
    # (the check_tree_cache_oblivious.py recipe) — when it has, the
    # audit degrades to a 1-way mesh rather than skipping
    if "jax" not in sys.modules:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=2"
            ).strip()
    problems: list = []

    if do_smoke:
        print("[check_cost_model] cross-validating the ledger against "
              "the traced census (shipped knob matrix, trace-only)")
        problems.extend(run_identity_matrix(verbose=args.verbose))
        if not args.skip_mutants:
            problems.extend(run_cost_mutant_controls())

    if do_grade:
        print("[check_cost_model] grading modeled winners against the "
              "banked trajectory")
        results, gp = grade_trajectory(args.trajectory)
        problems.extend(gp)
        print_grade_report(results)

    if problems:
        print(f"[check_cost_model] FAIL: {len(problems)} problem(s)")
        for p in problems:
            print(f"  - {p}")
        return 1
    scope = ("smoke" if do_smoke and not do_grade
             else "grade" if do_grade and not do_smoke else "full")
    print(f"[check_cost_model] PASS ({scope}): ledger == census "
          "bit-exactly per shape class; all undercount mutants caught"
          if do_smoke else
          f"[check_cost_model] PASS ({scope})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
