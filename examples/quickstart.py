"""grapevine-tpu quickstart: server + two clients, end to end.

Runs entirely in-process on the CPU backend (no TPU needed — the same
code drives a TPU engine unchanged). Demonstrates the full reference
workflow (reference README.md:126-175): attested-style Auth handshake,
challenge-signed queries, CRUD on fixed-size records, zero-id "next
message" semantics, and the expiry sweep.

    python examples/quickstart.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
# default to the CPU backend so the demo runs anywhere; set
# GRAPEVINE_PLATFORM=tpu to drive real hardware
_platform = os.environ.get("GRAPEVINE_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _platform
import jax

jax.config.update("jax_platforms", _platform)

from grapevine_tpu.config import GrapevineConfig
from grapevine_tpu.server.client import GrapevineClient
from grapevine_tpu.server.service import GrapevineServer
from grapevine_tpu.session.channel import ServerIdentity
from grapevine_tpu.wire import constants as C


def main():
    # -- server ---------------------------------------------------------
    cfg = GrapevineConfig(
        max_messages=1 << 10,     # bus capacity (power of two)
        max_recipients=256,
        batch_size=8,             # ops per oblivious round
        expiry_period=3600,       # seconds until messages expire
    )
    # a STABLE static key (IX handshake): clients pin it to reject MITM.
    # DEMO-ONLY SEED — anyone can derive this key. Production: derive
    # from a SECRET 32-byte seed (or ServerIdentity.generate()) and
    # distribute identity.public to clients out of band.
    identity = ServerIdentity.from_seed(b"demo-server-identity-seed-32byte")
    server = GrapevineServer(config=cfg, identity=identity)
    port = server.start("insecure-grapevine://127.0.0.1:0")
    print(f"server listening on insecure-grapevine://127.0.0.1:{port}")
    print(f"server static key (pin me): {identity.public.hex()[:16]}…")

    # -- clients: Alice and Bob -----------------------------------------
    # identity = a ristretto255 keypair derived from a 32-byte seed;
    # server_static pins the IX-authenticated server key (an active
    # MITM that substitutes its own identity is rejected at auth())
    alice = GrapevineClient(
        f"insecure-grapevine://127.0.0.1:{port}", identity_seed=b"A" * 32,
        server_static=identity.public,
    )
    bob = GrapevineClient(
        f"insecure-grapevine://127.0.0.1:{port}", identity_seed=b"B" * 32,
        server_static=identity.public,
    )
    alice.auth()  # IX handshake; pins the static, seeds the lockstep RNG
    bob.auth()
    print("clients authenticated (server pinned; challenge RNG in lockstep)")

    # -- create: Alice -> Bob -------------------------------------------
    payload = b"hello, oblivious world".ljust(C.PAYLOAD_SIZE, b"\x00")
    r = alice.create(recipient=bob.public_key, payload=payload)
    assert r.status_code == C.STATUS_CODE_SUCCESS
    msg_id = r.record.msg_id
    print(f"alice sent a message; server-assigned id {msg_id.hex()[:16]}…")

    # -- read: Bob pops his next message (zero id) ----------------------
    r = bob.read()  # id omitted = "give me my next message"
    assert r.status_code == C.STATUS_CODE_SUCCESS
    print(f"bob read: {r.record.payload.rstrip(chr(0).encode())!r}")

    # -- update: full-record replace by id ------------------------------
    r = alice.update(
        msg_id=msg_id,
        recipient=bob.public_key,
        payload=b"updated".ljust(C.PAYLOAD_SIZE, b"\x00"),
    )
    assert r.status_code == C.STATUS_CODE_SUCCESS

    # -- delete: Bob pops (deletes) it ----------------------------------
    r = bob.delete()  # zero id = pop next; indistinguishable from a read
    assert r.status_code == C.STATUS_CODE_SUCCESS
    r = bob.read()
    assert r.status_code == C.STATUS_CODE_NOT_FOUND  # inbox empty
    print("bob's inbox drained; absence and denial look identical")

    # -- expiry ---------------------------------------------------------
    alice.create(recipient=bob.public_key, payload=payload)
    evicted = server.engine.expire(int(time.time()) + 7200)
    print(f"expiry sweep evicted {evicted} record(s)")

    # -- aggregate health (never keyed by client identity) --------------
    h = server.health()
    print(
        f"health: rounds={h['rounds']} real_ops={h['real_ops']} "
        f"occupancy={h['batch_occupancy']:.2f} p99={h.get('round_ms_p99')}ms"
    )
    server.stop()
    print("done")


def main_tier():
    """The same workflow over the split serving tier (`--tier`):
    one engine process-equivalent + two frontends, Alice and Bob on
    DIFFERENT frontends, one shared oblivious bus (server/tier.py)."""
    from grapevine_tpu.server.tier import EngineServer, FrontendServer

    cfg = GrapevineConfig(max_messages=1 << 10, max_recipients=256, batch_size=8)
    engine = EngineServer(cfg)
    eport = engine.start("127.0.0.1:0")
    fe1 = FrontendServer(f"127.0.0.1:{eport}", config=cfg)
    fe2 = FrontendServer(f"127.0.0.1:{eport}", config=cfg)
    p1 = fe1.start("insecure-grapevine://127.0.0.1:0")
    p2 = fe2.start("insecure-grapevine://127.0.0.1:0")
    print(f"engine tier on :{eport}; frontends on :{p1} and :{p2}")

    alice = GrapevineClient(
        f"insecure-grapevine://127.0.0.1:{p1}", identity_seed=b"A" * 32,
        server_static=fe1.identity.public,
    )
    bob = GrapevineClient(
        f"insecure-grapevine://127.0.0.1:{p2}", identity_seed=b"B" * 32,
        server_static=fe2.identity.public,
    )
    alice.auth()
    bob.auth()
    payload = b"hello across the tier".ljust(C.PAYLOAD_SIZE, b"\x00")
    r = alice.create(recipient=bob.public_key, payload=payload)
    assert r.status_code == C.STATUS_CODE_SUCCESS
    r = bob.read()
    assert r.status_code == C.STATUS_CODE_SUCCESS
    print(f"bob (frontend 2) read alice's (frontend 1) message: "
          f"{r.record.payload.rstrip(chr(0).encode())!r}")
    r = bob.delete()
    assert r.status_code == C.STATUS_CODE_SUCCESS
    fe1.stop()
    fe2.stop()
    engine.stop()
    print("tier demo done")


if __name__ == "__main__":
    if "--tier" in sys.argv:
        main_tier()
    else:
        main()
