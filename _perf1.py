import sys, time
import numpy as np, jax, jax.numpy as jnp
from grapevine_tpu.config import GrapevineConfig
from grapevine_tpu.engine.state import EngineConfig, init_engine
from grapevine_tpu.engine.round_step import engine_round_step
from bench import make_batches

cap, bs = int(sys.argv[1]), int(sys.argv[2])
cfg = GrapevineConfig(max_messages=cap, max_recipients=1 << 12,
                      batch_size=bs, stash_size=max(224, bs // 2 + 96))
ecfg = EngineConfig.from_config(cfg)
state = init_engine(ecfg, seed=0)
step = jax.jit(engine_round_step, static_argnums=(0,), donate_argnums=(1,))
batches = [jax.device_put(b) for b in make_batches(4, bs)]
t0 = time.perf_counter()
state, resp, _ = step(ecfg, state, batches[0])
s0 = int(np.asarray(resp["status"]).sum())
print(f"compile+first: {time.perf_counter()-t0:.1f}s, statuses {s0}")
for i in range(6):
    t0 = time.perf_counter()
    state, resp, _ = step(ecfg, state, batches[(i+1) % 4])
    _ = int(np.asarray(resp["status"]).sum()) + int(np.asarray(state.rec.overflow))
    print(f"round: {(time.perf_counter()-t0)*1e3:.2f} ms (hard-synced)")
